"""Actor tests (modeled on reference python/ray/tests/test_actor.py)."""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def get(self):
        return self.n

    def fail(self):
        raise RuntimeError("method failure")

    def pid(self):
        import os

        return os.getpid()


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6


def test_actor_constructor_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.get.remote()) == 100


def test_actor_method_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_state_isolated(ray_start_regular):
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get(a.inc.remote())
    assert ray_tpu.get(b.get.remote()) == 0


def test_actor_method_exception(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(TaskError, match="method failure"):
        ray_tpu.get(c.fail.remote())
    # actor still alive afterwards
    assert ray_tpu.get(c.inc.remote()) == 1


def test_actor_handle_passing(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.inc.remote(10))

    assert ray_tpu.get(use.remote(c)) == 10
    assert ray_tpu.get(c.get.remote()) == 10


def test_named_actor(ray_start_regular):
    Counter.options(name="global_counter").remote(7)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.get.remote()) == 7


def test_named_actor_missing(ray_start_regular):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("does_not_exist")


def test_get_if_exists(ray_start_regular):
    a = Counter.options(name="gie").remote(1)
    b = Counter.options(name="gie", get_if_exists=True).remote(999)
    ray_tpu.get(a.inc.remote())
    assert ray_tpu.get(b.get.remote()) == 2


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.inc.remote())
    ray_tpu.kill(c)
    with pytest.raises((ActorDiedError, TaskError)):
        for _ in range(50):
            ray_tpu.get(c.inc.remote(), timeout=5)
            time.sleep(0.1)


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os

            os._exit(1)

    f = Flaky.remote()
    assert ray_tpu.get(f.inc.remote()) == 1
    f.die.remote()
    # After restart, state resets (fresh __init__) and calls succeed again.
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            v = ray_tpu.get(f.inc.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")
    assert v >= 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncActor:
        async def work(self, t):
            await asyncio.sleep(t)
            return t

        async def quick(self):
            return "fast"

    a = AsyncActor.remote()
    assert ray_tpu.get(a.quick.remote()) == "fast"  # wait for creation
    # concurrent execution: total time ~max not ~sum
    t0 = time.time()
    refs = [a.work.remote(0.5) for _ in range(4)]
    assert ray_tpu.get(refs) == [0.5] * 4
    assert time.time() - t0 < 1.5


def test_threaded_actor_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Blocking:
        def block(self, t):
            time.sleep(t)
            return t

    b = Blocking.remote()
    ray_tpu.get(b.block.remote(0))  # wait for creation
    t0 = time.time()
    ray_tpu.get([b.block.remote(0.5) for _ in range(4)])
    assert time.time() - t0 < 1.5


def test_actor_infeasible_resources(ray_start_regular):
    # Requesting more CPU than the cluster has → creation pends forever;
    # calls should not crash the runtime (we just check registration worked).
    h = Counter.options(num_cpus=64).remote()
    # the handle exists; the call stays pending — verify no crash within 1s
    ref = h.get.remote()
    ready, pending = ray_tpu.wait([ref], timeout=1)
    assert pending


def test_owner_fate_sharing(ray_start_regular):
    """Actors and placement groups created by a worker die with it
    (reference: gcs_actor_manager OnWorkerDead destroys owned actors)."""
    import time as _time

    from ray_tpu.util.placement_group import placement_group_table

    @ray_tpu.remote
    class Child:
        def ping(self):
            return "ok"

    @ray_tpu.remote
    class Owner:
        def setup(self):
            from ray_tpu.util.placement_group import placement_group

            self.child = Child.options(num_cpus=0).remote()
            ray_tpu.get(self.child.ping.remote())
            self.pg = placement_group([{"CPU": 1}])
            self.pg.ready(timeout=30)
            return self.child, self.pg.id

    owner = Owner.remote()
    child, pg_id = ray_tpu.get(owner.setup.remote())
    assert ray_tpu.get(child.ping.remote()) == "ok"
    ray_tpu.kill(owner)
    deadline = _time.time() + 30
    child_dead = pg_gone = False
    while _time.time() < deadline and not (child_dead and pg_gone):
        try:
            ray_tpu.get(child.ping.remote(), timeout=5)
        except Exception:
            child_dead = True
        table = placement_group_table()
        rec = table.get(pg_id.hex()) if isinstance(table, dict) else None
        pg_gone = rec is None or rec.get("state") == "REMOVED"
        _time.sleep(0.2)
    assert child_dead, "child actor outlived its owner"
    assert pg_gone, "placement group outlived its owner"
