"""ray_tpu.data streaming-subset tests (reference: python/ray/data/tests/)."""

import os
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def data_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_from_items_take_count(data_cluster):
    ds = rd.from_items(list(range(100)), override_num_blocks=4)
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]
    assert ds.num_blocks() == 4


def test_range_schema(data_cluster):
    ds = rd.range(1000, override_num_blocks=4)
    assert ds.count() == 1000
    assert "id" in ds.schema()


def test_map_batches_numpy(data_cluster):
    ds = rd.range(100, override_num_blocks=4).map_batches(
        lambda b: {"id": b["id"] * 2}
    )
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [2 * i for i in range(100)]


def test_map_filter_flat_map(data_cluster):
    ds = rd.from_items(list(range(20)), override_num_blocks=2)
    out = (
        ds.map(lambda x: x + 1)
        .filter(lambda x: x % 2 == 0)
        .flat_map(lambda x: [x, -x])
        .take_all()
    )
    expect = []
    for x in range(20):
        if (x + 1) % 2 == 0:
            expect.extend([x + 1, -(x + 1)])
    assert sorted(out) == sorted(expect)


def test_iter_batches_rechunk(data_cluster):
    ds = rd.range(1000, override_num_blocks=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=128)]
    assert sum(sizes) == 1000
    assert all(s == 128 for s in sizes[:-1])


def test_split_disjoint_equal(data_cluster):
    ds = rd.range(90, override_num_blocks=3)
    shards = ds.split(3, equal=True)
    all_ids = []
    for sh in shards:
        ids = [r["id"] for r in sh.take_all()]
        assert len(ids) == 30
        all_ids.extend(ids)
    assert sorted(all_ids) == list(range(90))


def test_random_shuffle(data_cluster):
    ds = rd.range(500, override_num_blocks=4).random_shuffle(seed=7)
    ids = [r["id"] for r in ds.take_all()]
    assert sorted(ids) == list(range(500))
    assert ids != list(range(500))


def test_read_parquet_roundtrip(data_cluster):
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = tempfile.mkdtemp()
    for i in range(3):
        t = pa.table({"x": np.arange(i * 10, (i + 1) * 10),
                      "y": np.arange(10) * 0.5})
        pq.write_table(t, os.path.join(d, f"part-{i}.parquet"))
    ds = rd.read_parquet(d)
    assert ds.count() == 30
    xs = sorted(r["x"] for r in ds.take_all())
    assert xs == list(range(30))
    doubled = ds.map_batches(lambda b: {"x2": b["x"] * 2}).take_all()
    assert sorted(r["x2"] for r in doubled) == [2 * i for i in range(30)]


def test_map_batches_actor_pool(data_cluster):
    class AddConst:
        def __init__(self, k):
            self.k = k

        def __call__(self, block):
            return {"id": block["id"] + self.k}

    ds = rd.range(200, override_num_blocks=8).map_batches(
        AddConst, fn_constructor_args=(1000,), concurrency=2,
    )
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == [1000 + i for i in range(200)]


def test_streaming_overlap(data_cluster):
    """Consumption starts before the full plan finishes: with 8 blocks of
    100ms map work on 8 CPUs and a slow consumer, the first batch must arrive
    in ~1 block-time, not ~all-blocks-time."""

    def slow_map(block):
        time.sleep(0.1)
        return block

    ds = rd.range(800, override_num_blocks=8).map_batches(slow_map)
    t0 = time.perf_counter()
    it = ds.iter_batches(batch_size=None, prefetch_blocks=2)
    first = next(it)
    t_first = time.perf_counter() - t0
    rest = list(it)
    t_all = time.perf_counter() - t0
    assert len(first["id"]) == 100
    assert t_first < 0.7 * t_all, (t_first, t_all)


def test_trainer_ingest_overlap(data_cluster):
    """JaxTrainer trains from a Dataset shard with streaming ingest: a 60ms
    map stage and a 30ms step overlap, so the wall clock beats the serial
    sum."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def slow_map(block):
        time.sleep(0.06)
        return block

    n_blocks = 8
    ds = rd.range(n_blocks * 64, override_num_blocks=n_blocks).map_batches(
        slow_map
    )

    def loop(config):
        from ray_tpu import train

        shard = train.get_dataset_shard("train")
        total = 0
        t0 = time.perf_counter()
        for batch in shard.iter_batches(batch_size=64, prefetch_blocks=4):
            time.sleep(0.03)  # the "train step"
            total += len(batch["id"])
        wall = time.perf_counter() - t0
        train.report({"rows": total, "wall": wall})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ingest",
                             storage_path=tempfile.mkdtemp()),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.metrics["rows"] == n_blocks * 64
    serial = n_blocks * (0.06 + 0.03)
    assert result.metrics["wall"] < serial * 0.9, (
        result.metrics["wall"], serial
    )


def test_split_edge_cases(data_cluster):
    ds = rd.range(10, override_num_blocks=2)
    shards = ds.split(4, equal=True)
    assert [s.count() for s in shards] == [2, 2, 2, 2]
    shards = ds.split(4, equal=False)
    assert [s.count() for s in shards] == [3, 3, 2, 2]
    with pytest.raises(ValueError):
        rd.range(3, override_num_blocks=1).split(4, equal=True)
    assert rd.from_items([]).count() == 0
    assert rd.range(0).take_all() == []


def test_lazy_union_and_block_split(data_cluster):
    a = rd.range(40, override_num_blocks=4).map_batches(
        lambda b: {"id": b["id"] + 1}
    )
    b = rd.from_numpy(np.arange(1000, 1020), column="id")
    u = a.union(b)
    assert u.count() == 60
    shards = u.split_blocks(2)
    got = sorted(
        r["id"] for sh in shards for r in sh.take_all()
    )
    assert got == sorted(
        [i + 1 for i in range(40)] + list(range(1000, 1020))
    )


def test_map_batches_fixed_batch_size_stays_lazy(data_cluster):
    calls = []

    def counting(block):
        return {"id": block["id"], "n": np.full(len(block["id"]), len(block["id"]))}

    ds = rd.range(100, override_num_blocks=4).map_batches(
        counting, batch_size=30
    )
    sizes = [int(b["n"][0]) for b in ds.iter_batches(batch_size=None)]
    assert sizes == [30, 30, 30, 10]
