"""Collective group tests (modeled on reference
util/collective/tests/single_node_cpu_tests/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@ray_tpu.remote
class Worker:
    def init(self, world, rank, group="default"):
        col.init_collective_group(world, rank, group_name=group)
        return rank

    def allreduce(self, x, group="default", op=col.ReduceOp.SUM):
        return col.allreduce(np.asarray(x, dtype=np.float32), group, op=op)

    def allgather(self, x, group="default"):
        return col.allgather(np.asarray(x, dtype=np.float32), group)

    def broadcast(self, x, src, group="default"):
        return col.broadcast(np.asarray(x, dtype=np.float32), src, group)

    def reducescatter(self, x, group="default"):
        return col.reducescatter(np.asarray(x, dtype=np.float32), group)

    def rank_info(self, group="default"):
        return (col.get_rank(group), col.get_collective_group_size(group))

    def p2p(self, peer, group="default"):
        r = col.get_rank(group)
        if r == 0:
            col.send(np.arange(5, dtype=np.int64) * 7, peer, group)
            return None
        return col.recv(0, group)

    def barrier(self, group="default"):
        col.barrier(group)
        return True

    def destroy(self, group="default"):
        col.destroy_collective_group(group)
        return True


@pytest.fixture(scope="module")
def world4():
    ray_tpu.init(num_cpus=6)
    workers = [Worker.remote() for _ in range(4)]
    ray_tpu.get([w.init.remote(4, i) for i, w in enumerate(workers)])
    yield workers
    ray_tpu.shutdown()


def test_rank_info(world4):
    infos = ray_tpu.get([w.rank_info.remote() for w in world4])
    assert infos == [(0, 4), (1, 4), (2, 4), (3, 4)]


def test_allreduce_sum(world4):
    data = [np.full(10, i + 1, np.float32) for i in range(4)]
    out = ray_tpu.get(
        [w.allreduce.remote(d) for w, d in zip(world4, data)]
    )
    for o in out:
        np.testing.assert_array_equal(o, np.full(10, 10.0, np.float32))


def test_allreduce_max(world4):
    data = [np.arange(8, dtype=np.float32) * (i + 1) for i in range(4)]
    out = ray_tpu.get(
        [w.allreduce.remote(d, "default", col.ReduceOp.MAX)
         for w, d in zip(world4, data)]
    )
    for o in out:
        np.testing.assert_array_equal(o, np.arange(8, dtype=np.float32) * 4)


def test_allgather(world4):
    out = ray_tpu.get(
        [w.allgather.remote(np.full(3, i, np.float32))
         for i, w in enumerate(world4)]
    )
    for gathered in out:
        assert len(gathered) == 4
        for i, g in enumerate(gathered):
            np.testing.assert_array_equal(g, np.full(3, i, np.float32))


def test_broadcast(world4):
    payload = np.arange(6, dtype=np.float32)
    out = ray_tpu.get(
        [w.broadcast.remote(payload if i == 1 else np.zeros(6), 1)
         for i, w in enumerate(world4)]
    )
    for o in out:
        np.testing.assert_array_equal(o, payload)


def test_reducescatter(world4):
    data = np.arange(8, dtype=np.float32)
    out = ray_tpu.get([w.reducescatter.remote(data) for w in world4])
    full = data * 4
    got = np.concatenate([out[(r + 1) % 4] for r in range(4)])
    # every element of the reduced vector appears exactly once across ranks
    np.testing.assert_array_equal(np.sort(got), np.sort(full))


def test_send_recv(world4):
    res = ray_tpu.get([world4[0].p2p.remote(1), world4[1].p2p.remote(1)])
    np.testing.assert_array_equal(res[1], np.arange(5, dtype=np.int64) * 7)


def test_barrier(world4):
    assert all(ray_tpu.get([w.barrier.remote() for w in world4]))


def test_create_collective_group_declarative(world4):
    workers = [Worker.remote() for _ in range(2)]
    col.create_collective_group(workers, 2, [0, 1], group_name="g2")
    out = ray_tpu.get(
        [w.allreduce.remote(np.ones(4), "g2") for w in workers]
    )
    for o in out:
        np.testing.assert_array_equal(o, np.full(4, 2.0, np.float32))
    ray_tpu.get([w.destroy.remote("g2") for w in workers])
