"""GCP TPU-VM node provider (autoscaler/gcp_tpu.py): recorded-command unit
tests with an injected gcloud runner (the reference mocks googleapiclient
the same way, python/ray/tests/gcp/test_gcp_node_provider.py), plus the
launcher glue, plus an executable fake-ssh-on-PATH test that drives
SSHCommandRunner through a real subprocess instead of a monkeypatch."""

import json
import os
import stat
import sys

import pytest

from ray_tpu.autoscaler.gcp_tpu import (
    GcpTpuNodeProvider,
    cluster_ips,
    teardown,
)

NODE_TYPES = {
    "head": {"accelerator_type": "v5litepod-8",
             "version": "tpu-ubuntu2204-base"},
    "worker": {"accelerator_type": "v5litepod-16",
               "version": "tpu-ubuntu2204-base", "spot": True},
}


class FakeGcloud:
    """Records every argv; answers list/describe from a mutable fleet."""

    def __init__(self):
        self.calls = []
        self.fleet = {}  # name -> {type, state, endpoints}

    def __call__(self, argv, timeout):
        self.calls.append(argv)
        assert argv[:5] == ["gcloud", "compute", "tpus", "tpu-vm", argv[4]]
        verb = argv[4]
        assert "--project" in argv and "--zone" in argv
        if verb == "create":
            name = argv[argv.index("--zone") + 2]
            labels = argv[argv.index("--labels") + 1]
            ntype = dict(kv.split("=") for kv in labels.split(","))[
                "rtpu-node-type"]
            n_hosts = 2 if ntype == "worker" else 1
            self.fleet[name] = {
                "type": ntype, "state": "READY",
                "endpoints": [f"10.0.{len(self.fleet)}.{i}"
                              for i in range(n_hosts)],
            }
            return ""
        if verb == "list":
            return json.dumps([
                {"name": f"projects/p/locations/z/nodes/{name}",
                 "state": rec["state"],
                 "labels": {"rtpu-cluster": "c1",
                            "rtpu-node-type": rec["type"]}}
                for name, rec in self.fleet.items()
            ])
        if verb == "describe":
            name = argv[argv.index("--zone") + 2]
            rec = self.fleet[name]
            return json.dumps({
                "name": name, "state": rec["state"],
                "networkEndpoints": [{"ipAddress": ip}
                                     for ip in rec["endpoints"]],
            })
        if verb == "delete":
            name = argv[argv.index("--zone") + 2]
            self.fleet.pop(name, None)
            return ""
        raise AssertionError(f"unexpected verb {verb}")


def _provider(fake):
    return GcpTpuNodeProvider(
        project="proj", zone="us-central2-b", cluster_name="c1",
        node_types=NODE_TYPES, runner=fake, timeout_s=5)


@pytest.mark.fast
def test_create_command_shape():
    fake = FakeGcloud()
    p = _provider(fake)
    (name,) = p.create_node("worker")
    assert name.startswith("c1-worker-")
    argv = fake.calls[0]
    assert argv[4] == "create"
    assert argv[argv.index("--accelerator-type") + 1] == "v5litepod-16"
    assert argv[argv.index("--version") + 1] == "tpu-ubuntu2204-base"
    assert "--spot" in argv
    assert ("rtpu-cluster=c1,rtpu-node-type=worker"
            == argv[argv.index("--labels") + 1])


@pytest.mark.fast
def test_list_filters_terminal_states_and_foreign_clusters():
    fake = FakeGcloud()
    p = _provider(fake)
    p.create_node("head")
    (w,) = p.create_node("worker")
    fake.fleet[w]["state"] = "PREEMPTED"
    nodes = p.non_terminated_nodes()
    assert list(nodes.values()) == ["head"]
    list_call = fake.calls[-1]
    assert ("labels.rtpu-cluster=c1"
            == list_call[list_call.index("--filter") + 1])


@pytest.mark.fast
def test_slice_hosts_expands_pod_endpoints():
    fake = FakeGcloud()
    p = _provider(fake)
    (w,) = p.create_node("worker")  # fake gives worker slices 2 hosts
    assert len(p.slice_hosts(w)) == 2


def test_cluster_ips_assembles_fleet_and_is_idempotent():
    fake = FakeGcloud()
    p = _provider(fake)
    config = {"provider": {"head_type": "head",
                           "worker_types": {"worker": 2}}}
    head, workers = cluster_ips(p, config)
    assert head and len(workers) == 4  # 2 slices x 2 hosts
    created = [c for c in fake.calls if c[4] == "create"]
    assert len(created) == 3  # 1 head + 2 workers
    # second call finds the fleet and creates nothing
    head2, workers2 = cluster_ips(p, config)
    assert (head2, sorted(workers2)) == (head, sorted(workers))
    assert len([c for c in fake.calls if c[4] == "create"]) == 3


def test_wait_ready_polls_until_ready():
    fake = FakeGcloud()
    p = _provider(fake)
    (h,) = p.create_node("head")
    fake.fleet[h]["state"] = "CREATING"
    flips = {"n": 0}
    orig = fake.__call__

    def flip(argv, timeout):
        if argv[4] == "describe":
            flips["n"] += 1
            if flips["n"] >= 3:
                fake.fleet[h]["state"] = "READY"
        return orig(argv, timeout)

    p._run = flip
    rec = p.wait_ready(h, poll_s=0.01, timeout_s=5)
    assert rec["state"] == "READY" and flips["n"] >= 3


def test_teardown_deletes_every_labelled_slice():
    fake = FakeGcloud()
    p = _provider(fake)
    p.create_node("head")
    p.create_node("worker", 2)
    gone = teardown(p)
    assert len(gone) == 3 and fake.fleet == {}


@pytest.mark.fast
def test_launcher_config_validation(tmp_path):
    import yaml

    from ray_tpu.autoscaler.launcher import LauncherError, load_cluster_config

    cfg = {"cluster_name": "c1",
           "provider": {"type": "gcp-tpu", "project": "p"}}
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump(cfg))
    with pytest.raises(LauncherError, match="zone"):
        load_cluster_config(str(path))
    cfg["provider"]["zone"] = "z"
    path.write_text(yaml.safe_dump(cfg))
    with pytest.raises(LauncherError, match="tpu_node_types"):
        load_cluster_config(str(path))
    cfg["tpu_node_types"] = NODE_TYPES
    path.write_text(yaml.safe_dump(cfg))
    assert load_cluster_config(str(path))["provider"]["type"] == "gcp-tpu"


def test_launcher_node_ips_uses_provider(monkeypatch, tmp_path):
    import yaml

    from ray_tpu.autoscaler import launcher

    fake = FakeGcloud()
    monkeypatch.setattr(launcher, "_gcp_provider",
                        lambda config: _provider(fake))
    cfg = {
        "cluster_name": "c1",
        "provider": {"type": "gcp-tpu", "project": "p", "zone": "z",
                     "head_type": "head", "worker_types": {"worker": 1}},
        "tpu_node_types": NODE_TYPES,
    }
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump(cfg))
    config = launcher.load_cluster_config(str(path))
    head, workers = launcher._node_ips(config)
    assert head and len(workers) == 2  # the worker slice has 2 hosts
    # `down`'s listing path sees the same fleet
    head2, workers2 = launcher._node_ips_cached_or_static(config)
    assert set([head2] + workers2) == set([head] + workers)


# ------------------------------------------------------- real-subprocess ssh


@pytest.fixture
def fake_ssh_on_path(tmp_path, monkeypatch):
    """An executable `ssh` shim that RUNS the remote command locally (and an
    `rsync` shim copying via cp). Unlike monkeypatching subprocess.run,
    this drives SSHCommandRunner's real argv through a real exec — flag
    parsing bugs and quoting bugs fail loudly. (A true loopback sshd test
    needs an sshd binary; this image ships none.)"""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    log = tmp_path / "ssh.log"
    ssh = bindir / "ssh"
    ssh.write_text(f"""#!{sys.executable}
import subprocess, sys
args = sys.argv[1:]
with open({str(log)!r}, "a") as f:
    f.write(repr(args) + "\\n")
# drop -o options and -i key
rest = []
i = 0
while i < len(args):
    if args[i] in ("-o", "-i"):
        i += 2
        continue
    rest.append(args[i]); i += 1
target, command = rest[0], " ".join(rest[1:])
assert "@" in target or target, target
proc = subprocess.run(["bash", "-c", command])
sys.exit(proc.returncode)
""")
    ssh.chmod(ssh.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    return log


def test_ssh_runner_through_real_exec(fake_ssh_on_path, tmp_path):
    from ray_tpu.autoscaler.launcher import SSHCommandRunner

    runner = SSHCommandRunner(
        "127.0.0.1", {"ssh_user": "u", "ssh_private_key": "~/.ssh/k"}, "c1")
    marker = tmp_path / "touched"
    out = runner.run(f"echo hello && touch {marker}",
                     env={"GREETING": "hi there"})
    assert "hello" in out
    assert marker.exists()  # the command really executed
    logged = fake_ssh_on_path.read_text()
    assert "u@127.0.0.1" in logged
    assert "ControlMaster=auto" in logged  # multiplexing opts reached exec
    # failures surface as LauncherError with the remote stderr
    from ray_tpu.autoscaler.launcher import LauncherError

    with pytest.raises(LauncherError, match="rc=3"):
        runner.run("exit 3")
