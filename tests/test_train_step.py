"""Sharded train-step tests on the virtual 8-device CPU mesh.

Checks every parallelism axis combination gives the same loss trajectory as
the single-device step (the shardings must be semantics-preserving — XLA only
changes where the FLOPs run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.parallel.mesh import make_mesh, single_axis_mesh
from ray_tpu.parallel.train_step import TrainStep

CFG = GPT2Config.tiny(use_flash_attention=False, dtype=jnp.float32)


def _batch(rng, B=8, T=64):
    idx = rng.integers(0, CFG.vocab_size, size=(B, T)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1)
    return {"idx": jnp.asarray(idx), "targets": jnp.asarray(tgt)}


def _run(mesh, steps=3):
    ts = TrainStep(CFG, mesh, learning_rate=1e-3)
    state = ts.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = ts.shard_batch(_batch(rng))
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def baseline():
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    return _run(mesh)


@pytest.mark.parametrize(
    "axes",
    [
        {"dp": 8},
        {"fsdp": 8},
        {"dp": 2, "fsdp": 4},
        {"tp": 8},
        {"dp": 2, "tp": 4},
        {"sp": 8},
        {"dp": 2, "sp": 4},
        {"dp": 2, "fsdp": 2, "tp": 2},
        {"dp": 2, "sp": 2, "tp": 2},
    ],
)
def test_parallel_matches_single_device(axes, baseline):
    base_losses, _ = baseline
    losses, _ = _run(make_mesh(axes))
    np.testing.assert_allclose(losses, base_losses, rtol=2e-3, atol=2e-3)
    assert losses[-1] < losses[0]  # it actually learns


def test_state_is_sharded():
    mesh = make_mesh({"fsdp": 4, "tp": 2})
    ts = TrainStep(CFG, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    kernel = state["params"]["h_0"]["attn"]["c_attn"]["kernel"]
    # column-parallel qkv kernel: sharded fsdp x tp
    assert len(kernel.sharding.device_set) == 8
    # adam mu follows the same sharding as the param
    mu = state["opt_state"][1][0].mu["h_0"]["attn"]["c_attn"]["kernel"]
    assert mu.sharding == kernel.sharding


def test_donation_and_step_counter():
    mesh = single_axis_mesh("dp")
    ts = TrainStep(CFG, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    state, _ = ts.step(state, ts.shard_batch(_batch(rng)))
    state, _ = ts.step(state, ts.shard_batch(_batch(rng)))
    assert int(state["step"]) == 2


def test_multi_step_matches_repeated_step():
    """One lax.scan dispatch of k steps must match k single-step calls
    (the dispatch-amortized path used on TPU)."""
    mesh = single_axis_mesh("dp")
    rng = np.random.default_rng(3)
    batch_np = _batch(rng)

    ts1 = TrainStep(CFG, mesh, learning_rate=1e-3)
    s1 = ts1.init(jax.random.PRNGKey(0))
    b1 = ts1.shard_batch(batch_np)
    for _ in range(4):
        s1, m1 = ts1.step(s1, b1)

    ts2 = TrainStep(CFG, mesh, learning_rate=1e-3)
    s2 = ts2.init(jax.random.PRNGKey(0))
    b2 = ts2.shard_batch(batch_np)
    s2, m2 = ts2.multi_step(s2, b2, 4)

    assert m2["loss"].shape == (4,)  # stacked per-step metrics
    np.testing.assert_allclose(float(m2["loss"][-1]), float(m1["loss"]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s1["params"]["wte"]["embedding"]),
        np.asarray(s2["params"]["wte"]["embedding"]),
        rtol=1e-5, atol=1e-5,
    )
    assert int(s2["step"]) == 4
    # second call reuses the compiled scan (cached dispatch path)
    s2, m2 = ts2.multi_step(s2, b2, 4)
    assert int(s2["step"]) == 8
