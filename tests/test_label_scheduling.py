"""Node-label scheduling tests (reference:
raylet/scheduling/policy/node_label_scheduling_policy.cc +
util/scheduling_strategies.py NodeLabelSchedulingStrategy)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy


@pytest.fixture(scope="module")
def label_cluster():
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"resources": {"CPU": 2},
                        "labels": {"zone": "a", "tier": "cpu"}},
    )
    cluster.add_node(resources={"CPU": 2},
                     labels={"zone": "b", "tier": "accel"})
    cluster.add_node(resources={"CPU": 2},
                     labels={"zone": "c", "tier": "accel"})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    node_by_zone = {}
    for n in ray_tpu.nodes():
        node_by_zone[n["Labels"].get("zone")] = n["NodeID"]
    yield node_by_zone
    ray_tpu.shutdown()
    cluster.shutdown()


@ray_tpu.remote
def where():
    return ray_tpu.get_runtime_context().get_node_id()


def test_hard_label_routes_task(label_cluster):
    node_by_zone = label_cluster
    for zone in ("a", "b", "c"):
        nid = ray_tpu.get(
            where.options(
                scheduling_strategy=NodeLabelSchedulingStrategy(
                    hard={"zone": zone})
            ).remote(),
            timeout=60,
        )
        assert nid == node_by_zone[zone]


def test_hard_label_no_match_errors(label_cluster):
    ref = where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(hard={"zone": "zz"})
    ).remote()
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=60)


def test_soft_label_prefers_match(label_cluster):
    node_by_zone = label_cluster
    nid = ray_tpu.get(
        where.options(
            scheduling_strategy=NodeLabelSchedulingStrategy(
                hard={"tier": "accel"}, soft={"zone": "c"})
        ).remote(),
        timeout=60,
    )
    assert nid == node_by_zone["c"]


def test_label_actor_placement(label_cluster):
    node_by_zone = label_cluster

    @ray_tpu.remote
    class Pin:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = Pin.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(hard={"zone": "b"})
    ).remote()
    assert ray_tpu.get(a.node.remote(), timeout=60) == node_by_zone["b"]
    ray_tpu.kill(a)
