"""serve.llm — continuous-batching inference engine.

Layers under test, bottom up: the paged KV allocator (block tables,
alloc/free/exhaustion), the iteration-level scheduler (admit / fused
decode / preempt-requeue / terminate) driven with a fake model, the
engine's end-to-end token streams (including byte-equivalence of the
batched engine vs unbatched generation, and vs the flax gpt2/llama
forward), admission control's structured backpressure, and the serve
integration (OOB ingress streams, cancellation freeing KV, the
@serve.batch satellite fixes).
"""

import asyncio
import time

import numpy as np
import pytest

from ray_tpu.serve.llm.adapters import FakeAdapter, build_adapter
from ray_tpu.serve.llm.engine import (
    LLMBackpressure,
    LLMEngine,
    SamplingParams,
)
from ray_tpu.serve.llm.kv_cache import PagedKVCache
from ray_tpu.serve.llm.scheduler import Scheduler, Sequence


def _cache(num_blocks=8, block_size=4, n_layers=2, heads=1, dim=2):
    return PagedKVCache(num_blocks=num_blocks, block_size=block_size,
                        n_layers=n_layers, n_kv_heads=heads, head_dim=dim)


# ------------------------------------------------------------------ KV cache


def test_kv_alloc_free_exhaustion():
    c = _cache(num_blocks=4, block_size=4)
    assert c.allocate("a", 6)            # ceil(6/4) = 2 blocks
    assert c.num_used_blocks == 2 and c.utilization() == 0.5
    assert c.allocate("b", 8)            # 2 more
    assert not c.allocate("c", 1)        # pool exhausted, refused cleanly
    assert "c" not in c.block_tables
    with pytest.raises(ValueError):
        c.allocate("a", 1)               # double-alloc is a bug
    assert c.free("a") == 2
    assert c.allocate("c", 4)
    c.free("b"), c.free("c")
    assert c.num_free_blocks == 4 and c.free("nope") == 0


def test_kv_block_table_roundtrip_across_boundaries():
    c = _cache(num_blocks=16, block_size=4, n_layers=3, heads=2, dim=5)
    rng = np.random.default_rng(0)
    assert c.allocate("s", 7)
    k = rng.normal(size=(3, 7, 2, 5)).astype(np.float32)
    v = rng.normal(size=(3, 7, 2, 5)).astype(np.float32)
    c.write_prefill("s", k, v)           # spans 2 blocks
    gk, gv = c.gather("s")
    np.testing.assert_array_equal(gk, k)
    np.testing.assert_array_equal(gv, v)
    # append across a block boundary (7 -> 8 fills block 2; 8 -> 9 opens 3)
    for i in range(2):
        kn = rng.normal(size=(3, 2, 5)).astype(np.float32)
        assert c.extend("s", 1)
        c.append("s", kn, kn * 2)
        gk, gv = c.gather("s")
        np.testing.assert_array_equal(gk[:, -1], kn)
        np.testing.assert_array_equal(gv[:, -1], kn * 2)
    assert c.seq_lens["s"] == 9 and len(c.block_tables["s"]) == 3


def test_kv_gather_batch_padding_and_masking():
    c = _cache(num_blocks=8, block_size=2, n_layers=1, heads=1, dim=1)
    for sid, toks in (("a", [3.0, 4.0, 5.0]), ("b", [7.0])):
        assert c.allocate(sid, len(toks))
        arr = np.asarray(toks, np.float32).reshape(1, -1, 1, 1)
        c.write_prefill(sid, arr, arr)
    k, v, lens = c.gather_batch(["a", "b"])
    assert k.shape == (2, 1, 3, 1, 1) and lens.tolist() == [3, 1]
    assert k[0, 0, :, 0, 0].tolist() == [3.0, 4.0, 5.0]
    assert k[1, 0, 0, 0, 0] == 7.0      # positions past lens are undefined


def test_kv_failed_extend_is_side_effect_free():
    c = _cache(num_blocks=2, block_size=2)
    assert c.allocate("a", 3)           # uses both blocks, capacity 4
    arr = np.zeros((2, 3, 1, 2), np.float32)
    c.write_prefill("a", arr, arr)      # len 3 of 4
    assert c.extend("a", 1)             # fits the last slot, no new block
    assert not c.extend("a", 2)         # would need a block; none left
    assert len(c.block_tables["a"]) == 2  # rolled back cleanly


# ----------------------------------------------------------------- scheduler


def test_scheduler_admit_batch_cap_and_finish():
    c = _cache(num_blocks=64, block_size=4)
    s = Scheduler(c, max_batch_size=2, max_waiting=16)
    seqs = [Sequence(prompt=[1, 2], max_tokens=2) for _ in range(3)]
    for q in seqs:
        s.add(q)
    plan = s.schedule()
    assert [x.seq_id for x in plan.prefills] == [seqs[0].seq_id,
                                                 seqs[1].seq_id]
    assert len(s.waiting) == 1          # batch cap holds the third back
    s.commit({q.seq_id: 5 for q in plan.prefills})
    plan2 = s.schedule()                # batch still full: no admit yet
    assert plan2.prefills == [] and len(plan2.decodes) == 2
    # second token hits max_tokens for the first two -> finish + free
    done = s.commit({q.seq_id: 6 for q in plan2.decodes})
    assert {q.seq_id for q in done} == {seqs[0].seq_id, seqs[1].seq_id}
    assert all(q.finish_reason == "length" for q in done)
    assert seqs[0].seq_id not in c.block_tables  # blocks freed on finish
    plan3 = s.schedule()                # freed slots -> the third admits
    assert [x.seq_id for x in plan3.prefills] == [seqs[2].seq_id]


def test_scheduler_eos_termination():
    c = _cache(num_blocks=64, block_size=4)
    s = Scheduler(c, max_batch_size=4)
    q = Sequence(prompt=[1], max_tokens=100, eos_id=9)
    s.add(q)
    s.schedule()
    done = s.commit({q.seq_id: 9})
    assert done and done[0].finish_reason == "eos"


def test_scheduler_preempts_youngest_and_requeues():
    # 4 blocks of 2: two sequences of prompt 3 (2 blocks each) fill the pool
    c = _cache(num_blocks=4, block_size=2)
    s = Scheduler(c, max_batch_size=4)
    old = Sequence(prompt=[1, 2, 3], max_tokens=8)
    young = Sequence(prompt=[4, 5, 6], max_tokens=8)
    s.add(old), s.add(young)
    plan = s.schedule()
    assert len(plan.prefills) == 2
    c.seq_lens[old.seq_id] = 4          # simulate prefill+decode fills
    c.seq_lens[young.seq_id] = 4        # both now need a new block next step
    s.commit({old.seq_id: 1, young.seq_id: 1})
    plan = s.schedule()
    # no free blocks: the YOUNGEST is evicted to fund the oldest
    assert [x.seq_id for x in plan.preempted] == [young.seq_id]
    assert young.state == "WAITING" and young.preemptions == 1
    assert s.preemptions_total == 1
    assert young.seq_id not in c.block_tables      # its blocks came back
    assert [x.seq_id for x in plan.decodes] == [old.seq_id]
    # the preempted context folds generated tokens in for the re-prefill
    assert young.context_tokens() == [4, 5, 6, 1]


def test_scheduler_cancel_waiting_and_running():
    c = _cache(num_blocks=64, block_size=4)
    s = Scheduler(c, max_batch_size=1)
    a = Sequence(prompt=[1], max_tokens=8)
    b = Sequence(prompt=[2], max_tokens=8)
    s.add(a), s.add(b)
    s.schedule()                         # a runs, b waits
    assert s.cancel(b.seq_id)            # waiting: finished immediately
    assert b.state == "FINISHED" and b.finish_reason == "cancelled"
    assert s.cancel(a.seq_id)            # running: reaped at next schedule
    plan = s.schedule()
    assert [x.seq_id for x in plan.reaped] == [a.seq_id]
    assert a.seq_id not in c.block_tables
    assert not s.has_work() and not s.cancel(a.seq_id)


# -------------------------------------------------------------------- engine


def _drain_outputs(eng, rids):
    eng.run_until_drained()
    out = []
    for r in rids:
        toks, done, reason = eng.pull(r)
        assert done
        out.append((toks, reason))
    return out


def test_engine_batched_equals_unbatched():
    big = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
                    max_batch=8, max_waiting=32)
    rids = [big.submit([1, 2, 3], SamplingParams(max_tokens=12))
            for _ in range(6)]
    batched = _drain_outputs(big, rids)
    one = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
                    max_batch=1, max_waiting=32)
    r = one.submit([1, 2, 3], SamplingParams(max_tokens=12))
    (ref, reason), = _drain_outputs(one, [r])
    assert reason == "length" and len(ref) == 12
    assert all(t == (ref, "length") for t in batched)


def test_engine_preemption_recompute_equivalence():
    ref_eng = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                        block_size=4, max_batch=4)
    ref = _drain_outputs(
        ref_eng, [ref_eng.submit([7, 8], SamplingParams(max_tokens=10))]
    )[0][0]
    tiny = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=7, block_size=2,
                     max_batch=4, max_waiting=32)
    rids = [tiny.submit([7, 8], SamplingParams(max_tokens=10))
            for _ in range(3)]
    outs = _drain_outputs(tiny, rids)
    assert tiny.scheduler.preemptions_total > 0   # the tiny pool did evict
    assert all(o == (ref, "length") for o in outs)
    assert tiny.cache.num_used_blocks == 0        # everything freed


def test_engine_admission_backpressure_structured():
    import cloudpickle

    eng = LLMEngine(FakeAdapter(), num_blocks=16, block_size=4,
                    max_batch=1, max_waiting=2)
    eng.submit([1]), eng.submit([2])
    with pytest.raises(LLMBackpressure) as ei:
        eng.submit([3])
    e = ei.value
    assert e.queue_depth == 2 and e.max_waiting == 2
    assert e.to_dict()["backpressure"] is True
    # crosses the actor boundary intact (proxy relies on the structure)
    e2 = cloudpickle.loads(cloudpickle.dumps(e))
    assert isinstance(e2, LLMBackpressure) and e2.queue_depth == 2


def test_engine_rejects_impossible_prompts():
    eng = LLMEngine(FakeAdapter(vocab_size=10), num_blocks=2, block_size=2,
                    max_batch=1)
    with pytest.raises(ValueError):
        eng.submit([])
    with pytest.raises(ValueError):
        eng.submit([11])                  # out of vocab
    with pytest.raises(ValueError):
        eng.submit([1] * 10)              # can never fit 2x2 cache


def test_engine_cancel_mid_stream_frees_kv():
    eng = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=32, block_size=2,
                    max_batch=4)
    keep = eng.submit([1, 2], SamplingParams(max_tokens=6))
    drop = eng.submit([3, 4], SamplingParams(max_tokens=50))
    eng.step()                            # both admitted, 1 token each
    assert eng.cache.num_used_blocks > 0
    assert eng.cancel(drop)
    toks, done, reason = eng.pull(drop)
    assert done and reason == "cancelled"
    eng.run_until_drained()               # reaps drop, finishes keep
    toks, done, reason = eng.pull(keep)
    assert done and reason == "length" and len(toks) == 6
    assert eng.cache.num_used_blocks == 0
    assert eng.scheduler.queue_depth() == 0


def test_engine_temperature_sampling_seeded():
    mk = lambda: LLMEngine(FakeAdapter(vocab_size=97), num_blocks=32,
                           block_size=4, max_batch=2)
    sp = dict(max_tokens=8, temperature=1.0)
    a = _drain_outputs(*(lambda e: (e, [e.submit([1, 2],
        SamplingParams(seed=7, **sp))]))(mk()))[0][0]
    b = _drain_outputs(*(lambda e: (e, [e.submit([1, 2],
        SamplingParams(seed=7, **sp))]))(mk()))[0][0]
    c = _drain_outputs(*(lambda e: (e, [e.submit([1, 2],
        SamplingParams(seed=8, **sp))]))(mk()))[0][0]
    assert a == b and len(a) == 8
    assert a != c                         # 97^8 — a collision means a bug


# ----------------------------------------------------- model-zoo equivalence


def test_gpt2_streamed_equals_oneshot_forward():
    """The engine's incremental paged-KV decode must reproduce the flax
    model's full-context greedy generation token for token (fp32)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2 as g

    ad = build_adapter(
        "gpt2-tiny",
        {"n_layer": 2, "n_embd": 64, "n_head": 4, "vocab_size": 96,
         "block_size": 64, "use_flash_attention": False}, seed=0)
    prompt, n = [5, 9, 17, 3], 8
    params = jax.tree.map(jnp.asarray, ad.p)
    ctx = list(prompt)
    for _ in range(n):
        logits = g.forward(ad.cfg, params, jnp.asarray([ctx]))
        ctx.append(int(jnp.argmax(logits[0, -1])))
    ref = ctx[len(prompt):]

    eng = LLMEngine(ad, num_blocks=32, block_size=4, max_batch=4)
    rids = [eng.submit(prompt, SamplingParams(max_tokens=n))
            for _ in range(3)]          # batched alongside copies of itself
    outs = _drain_outputs(eng, rids)
    assert all(o == (ref, "length") for o in outs)


def test_llama_adapter_matches_forward():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama as L

    ad = build_adapter("llama-tiny",
                       {"vocab_size": 96, "block_size": 64,
                        "use_flash_attention": False}, seed=1)
    prompt, n = [5, 9, 17, 3], 6
    params = jax.tree.map(jnp.asarray, ad.p)
    ctx = list(prompt)
    for _ in range(n):
        logits = L.forward(ad.cfg, params, jnp.asarray([ctx]))
        ctx.append(int(jnp.argmax(logits[0, -1])))
    eng = LLMEngine(ad, num_blocks=32, block_size=4, max_batch=2)
    r = eng.submit(prompt, SamplingParams(max_tokens=n))
    (toks, _), = _drain_outputs(eng, [r])
    assert toks == ctx[len(prompt):]


def test_moe_adapter_generates_deterministically():
    ad = build_adapter("gpt2-moe-tiny",
                       {"n_layer": 2, "n_embd": 64, "n_head": 4,
                        "vocab_size": 96, "block_size": 64,
                        "use_flash_attention": False}, seed=2)
    eng = LLMEngine(ad, num_blocks=32, block_size=4, max_batch=2)
    r1 = eng.submit([5, 9], SamplingParams(max_tokens=5))
    r2 = eng.submit([5, 9], SamplingParams(max_tokens=5))
    o1, o2 = _drain_outputs(eng, [r1, r2])
    assert o1 == o2 and len(o1[0]) == 5


# ------------------------------------------------- @serve.batch (satellites)


def test_batch_stale_flusher_timer_cancelled():
    """A size-triggered flush must cancel the pending timeout timer, or
    the orphan fires early and flushes the NEXT partial batch before its
    own batch_wait_timeout_s."""
    from ray_tpu.serve.batching import batch

    async def main():
        calls = []

        class M:
            @batch(max_batch_size=2, batch_wait_timeout_s=0.25)
            async def f(self, items):
                calls.append(list(items))
                return [i * 10 for i in items]

        m = M()
        t0 = time.perf_counter()
        a = asyncio.ensure_future(m.f(1))
        b = asyncio.ensure_future(m.f(2))   # size flush; timer was pending
        await asyncio.sleep(0.05)
        c = asyncio.ensure_future(m.f(3))   # new partial batch
        assert await c == 30
        dt = time.perf_counter() - t0
        assert await a == 10 and await b == 20
        assert dt >= 0.25, f"stale timer flushed the new batch at {dt:.3f}s"
        assert calls == [[1, 2], [3]]

    asyncio.run(main())


def test_batch_cancelled_waiter_dropped():
    from ray_tpu.serve.batching import batch

    async def main():
        calls = []

        class M:
            @batch(max_batch_size=4, batch_wait_timeout_s=0.05)
            async def f(self, items):
                calls.append(list(items))
                return [i * 10 for i in items]

        m = M()
        d = asyncio.ensure_future(m.f(4))
        await asyncio.sleep(0)
        d.cancel()                         # client disconnected while queued
        e = asyncio.ensure_future(m.f(5))
        assert await e == 50
        assert calls == [[5]]              # 4 was never computed

    asyncio.run(main())


def test_batch_queue_lives_on_the_instance():
    """Queues keyed by id(instance) cross-wire when CPython reuses the id
    after a replica dies; storing the queue on the instance makes its
    lifetime exactly the replica's."""
    from ray_tpu.serve.batching import batch

    async def main():
        class M:
            @batch(max_batch_size=1, batch_wait_timeout_s=0.01)
            async def f(self, items):
                return [i + 1 for i in items]

        m1 = M()
        assert await m1.f(1) == 2
        assert getattr(m1, "__serve_batch_queue_f", None) is not None
        m2 = M()                           # fresh replica: fresh queue
        assert getattr(m2, "__serve_batch_queue_f", None) is None
        assert await m2.f(2) == 3
        assert (m1.__serve_batch_queue_f is not m2.__serve_batch_queue_f)

    asyncio.run(main())


# ------------------------------------------------------- serve integration


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.mark.timeout(170)
def test_serve_llm_smoke_8_streams(serve_cluster):
    """Tier-1 smoke: a small real model (gpt2-tiny) behind serve.llm, 8
    concurrent token streams through the zero-copy OOB ingress, plus the
    admission-shed and cancel paths on a second (fake-model) app."""
    import threading

    from ray_tpu.serve import llm
    from ray_tpu.serve.rpc_ingress import RpcBackpressureError

    h = llm.deploy(model="gpt2-tiny",
                   model_config={"n_layer": 2, "n_embd": 64, "n_head": 4,
                                 "vocab_size": 96, "block_size": 128,
                                 "use_flash_attention": False},
                   app_name="llm", num_blocks=256, block_size=8,
                   max_batch=8, max_waiting=64)
    ref = h.remote([5, 9, 17], max_tokens=12).result(timeout=60)
    assert len(ref["tokens"]) == 12 and ref["finish_reason"] == "length"

    results = [None] * 8

    def worker(i):
        results[i] = list(llm.stream([5, 9, 17], app_name="llm",
                                     max_tokens=12))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(r == ref["tokens"] for r in results), results

    stats = h.stats.remote().result(timeout=30)
    assert stats["tokens_total"] >= 9 * 12
    assert stats["waiting"] == 0 and stats["running"] == 0
    assert stats["kv_utilization"] == 0.0

    # admission shed + cancel on a cheap fake-model app in the same cluster
    h2 = llm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.1},
                    app_name="llm2", route_prefix="/llm2",
                    num_blocks=64, block_size=4, max_batch=1, max_waiting=2)
    streams, bp = [], None
    for _ in range(6):
        try:
            streams.append(llm.stream([1, 2, 3], app_name="llm2",
                                      max_tokens=40))
        except RpcBackpressureError as e:
            bp = e
            break
    assert bp is not None and bp.queue_depth >= bp.max_waiting == 2
    next(streams[0])                      # stream is live
    for s in streams:
        s.close()                         # mid-stream cancel through ingress
    deadline = time.time() + 30
    while time.time() < deadline:
        st = h2.stats.remote().result(timeout=30)
        if (st["waiting"] == 0 and st["running"] == 0
                and st["kv_utilization"] == 0.0):
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"cancelled streams did not free KV: {st}")


def test_replica_llm_hooks_direct():
    """The Replica wrapper's identity/load hooks and ungated llm_call
    dispatch, without booting a cluster."""
    import cloudpickle

    from ray_tpu.serve._replica import Replica

    class Eng:
        def __init__(self):
            self.identity = None

        def __serve_identity__(self, dep, replica):
            self.identity = (dep, replica)

        def __serve_load__(self):
            return 7

        async def llm_pull(self, rid, max_tokens=0):
            return {"rid": rid, "max": max_tokens}

    r = Replica({"callable": cloudpickle.dumps(Eng), "name": "dep"}, (), {})
    assert r._callable.identity == ("dep", "")
    assert r._extra_load() == 7
    out = asyncio.run(r.llm_call("llm_pull", ("x",), {"max_tokens": 3}))
    assert out == {"rid": "x", "max": 3}
