"""ray_tpu.util.multiprocessing Pool shim tests
(reference: python/ray/tests/test_multiprocessing.py subset)."""

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture(scope="module")
def pool_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def test_map_starmap(pool_cluster):
    with Pool(4) as p:
        assert p.map(_sq, range(20)) == [x * x for x in range(20)]
        assert p.starmap(_add, [(1, 2), (3, 4), (5, 6)]) == [3, 7, 11]


def test_apply_and_async(pool_cluster):
    with Pool(2) as p:
        assert p.apply(_add, (2, 3)) == 5
        r = p.apply_async(_sq, (9,))
        assert r.get(timeout=30) == 81
        m = p.map_async(_sq, [1, 2, 3])
        assert m.get(timeout=30) == [1, 4, 9]
        assert m.ready() and m.successful()


def test_imap_ordered_and_unordered(pool_cluster):
    with Pool(4) as p:
        assert list(p.imap(_sq, range(10), chunksize=3)) == [
            x * x for x in range(10)
        ]
        assert sorted(p.imap_unordered(_sq, range(10), chunksize=2)) == [
            x * x for x in range(10)
        ]


def test_closed_pool_rejects(pool_cluster):
    p = Pool(2)
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])
    p.terminate()
    p.join()
