"""Serialization-layer unit tests (no cluster needed)."""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef

pytestmark = pytest.mark.fast  # pure-unit: no cluster boot


def roundtrip(value):
    p, bufs, refs = serialization.serialize(value)
    out, out_refs = serialization.deserialize(p, bufs)
    return out


def test_primitives():
    for v in [1, 2.5, "s", b"b", None, True, [1, 2], {"a": 1}, (1, 2), {1, 2}]:
        assert roundtrip(v) == v


def test_numpy_out_of_band():
    arr = np.random.rand(100, 100)
    p, bufs, _ = serialization.serialize(arr)
    assert len(bufs) >= 1  # buffer went out-of-band, not into the pickle
    out, _ = serialization.deserialize(p, bufs)
    assert np.array_equal(out, arr)


def test_object_ref_capture():
    ref = ObjectRef(ObjectID.from_random(), ("127.0.0.1", 1234), skip_refcount=True)
    p, bufs, refs = serialization.serialize({"nested": [ref]})
    assert refs == [ref]
    out, out_refs = serialization.deserialize(p, bufs)
    assert out["nested"][0] == ref
    assert out_refs[0] == ref
    assert out_refs[0].owner_address == ("127.0.0.1", 1234)


def test_blob_roundtrip():
    value = {"arr": np.arange(10000), "meta": "x"}
    blob = serialization.serialize_to_blob(value)
    out, _ = serialization.read_blob(memoryview(blob))
    assert np.array_equal(out["arr"], value["arr"])
    assert out["meta"] == "x"


def test_blob_buffer_alignment():
    arr = np.arange(1000, dtype=np.float64)
    p, bufs, _ = serialization.serialize(arr)
    blob = bytearray(serialization.blob_size(p, bufs))
    serialization.write_blob(memoryview(blob), p, bufs)
    out, _ = serialization.read_blob(memoryview(bytes(blob)))
    assert np.array_equal(out, arr)


def test_closure_function():
    x = 42

    def f(y):
        return x + y

    g = roundtrip(f)
    assert g(1) == 43


def test_inline_roundtrip():
    msg, refs = serialization.serialize_inline([1, np.ones(5)])
    out, _ = serialization.deserialize_inline(msg)
    assert out[0] == 1 and np.array_equal(out[1], np.ones(5))


def test_serialize_keeps_buffers_raw():
    """serialize() must hand back the protocol-5 buffers RAW — views that
    alias the source array, never bytes copies (the zero-copy put path
    depends on it)."""
    arr = np.arange(4096, dtype=np.float64)
    p, bufs, _ = serialization.serialize(arr)
    assert len(bufs) == 1
    assert not isinstance(bufs[0], (bytes, bytearray))
    alias = np.frombuffer(memoryview(bufs[0]).cast("B"), dtype=np.uint8)
    assert np.shares_memory(alias, arr.view(np.uint8))


def test_blob_zero_length_buffer():
    """Empty arrays still emit an out-of-band buffer; the blob format must
    round-trip length-0 buffers (header + 0 payload bytes)."""
    value = {"empty": np.array([], dtype=np.float64),
             "also": np.zeros((0, 3), dtype=np.int32), "x": 1}
    blob = serialization.serialize_to_blob(value)
    out, _ = serialization.read_blob(memoryview(blob))
    assert out["x"] == 1
    assert out["empty"].shape == (0,) and out["empty"].dtype == np.float64
    assert out["also"].shape == (0, 3) and out["also"].dtype == np.int32


def test_blob_alignment_mixed_dtypes():
    """Every buffer in the blob sits on a 64-byte boundary regardless of
    the (odd-sized) buffers before it, so numpy/jax can map them directly."""
    value = {
        "i8": np.arange(7, dtype=np.int8),        # 7 bytes, breaks alignment
        "f64": np.arange(5, dtype=np.float64),
        "u16": np.arange(3, dtype=np.uint16),     # 6 bytes
        "empty": np.array([], dtype=np.float32),  # 0 bytes
        "f32": np.arange(9, dtype=np.float32),
    }
    p, bufs, _ = serialization.serialize(value)
    blob = serialization.serialize_to_blob(value)
    # parse offsets by hand and check alignment of every buffer start
    import struct

    src = memoryview(blob).cast("B")
    _, plen = struct.unpack_from("<II", src, 0)
    off = 8 + plen
    (nbuf,) = struct.unpack_from("<I", src, off)
    off += 4
    assert nbuf == len(bufs)
    for _ in range(nbuf):
        (blen,) = struct.unpack_from("<Q", src, off)
        off += 8
        off = (off + 63) & ~63
        assert off % 64 == 0
        off += blen
    out, _ = serialization.read_blob(memoryview(blob))
    for k, v in value.items():
        assert np.array_equal(out[k], v), k
        assert out[k].dtype == v.dtype


def test_blob_roundtrip_multi_chunk_sized():
    """An object larger than several object_manager_chunk_size units
    round-trips byte-for-byte (the transfer path slices the blob at chunk
    boundaries; the content must be boundary-agnostic)."""
    from ray_tpu._private.config import RTPU_CONFIG

    chunk = RTPU_CONFIG.object_manager_chunk_size
    n = 3 * chunk + 12345  # 3 full chunks + ragged tail
    rng = np.random.default_rng(7)
    value = rng.integers(0, 255, size=n, dtype=np.uint8)
    blob = serialization.serialize_to_blob(value)
    assert len(blob) > 3 * chunk
    # reassemble from chunk-sized slices like the transfer endpoints do
    reassembled = bytearray(len(blob))
    for off in range(0, len(blob), chunk):
        piece = memoryview(blob)[off:off + chunk]
        reassembled[off:off + piece.nbytes] = piece
    out, _ = serialization.read_blob(memoryview(reassembled))
    assert np.array_equal(out, value)


def test_serialize_to_blob_no_final_copy():
    """serialize_to_blob returns the exact-size bytearray it wrote into —
    no trailing bytes() copy of the whole object."""
    value = np.arange(10000)
    blob = serialization.serialize_to_blob(value)
    assert isinstance(blob, bytearray)
    assert len(blob) == serialization.blob_size(
        *serialization.serialize(value)[:2])


def test_read_blob_buffer_wrapper():
    """read_blob's buffer_wrapper sees every out-of-band buffer (and only
    those) — the worker relies on it to pin plasma memory."""
    wrapped = []

    def wrapper(mv):
        wrapped.append(mv.nbytes)
        return mv

    value = {"a": np.arange(100, dtype=np.float64), "b": "no-buffer"}
    blob = serialization.serialize_to_blob(value)
    out, _ = serialization.read_blob(memoryview(blob), buffer_wrapper=wrapper)
    assert np.array_equal(out["a"], value["a"]) and out["b"] == "no-buffer"
    assert wrapped == [800]

    # no out-of-band buffers -> wrapper never called
    wrapped.clear()
    blob = serialization.serialize_to_blob({"just": "strings"})
    out, _ = serialization.read_blob(memoryview(blob), buffer_wrapper=wrapper)
    assert wrapped == []
