"""Serialization-layer unit tests (no cluster needed)."""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef

pytestmark = pytest.mark.fast  # pure-unit: no cluster boot


def roundtrip(value):
    p, bufs, refs = serialization.serialize(value)
    out, out_refs = serialization.deserialize(p, bufs)
    return out


def test_primitives():
    for v in [1, 2.5, "s", b"b", None, True, [1, 2], {"a": 1}, (1, 2), {1, 2}]:
        assert roundtrip(v) == v


def test_numpy_out_of_band():
    arr = np.random.rand(100, 100)
    p, bufs, _ = serialization.serialize(arr)
    assert len(bufs) >= 1  # buffer went out-of-band, not into the pickle
    out, _ = serialization.deserialize(p, bufs)
    assert np.array_equal(out, arr)


def test_object_ref_capture():
    ref = ObjectRef(ObjectID.from_random(), ("127.0.0.1", 1234), skip_refcount=True)
    p, bufs, refs = serialization.serialize({"nested": [ref]})
    assert refs == [ref]
    out, out_refs = serialization.deserialize(p, bufs)
    assert out["nested"][0] == ref
    assert out_refs[0] == ref
    assert out_refs[0].owner_address == ("127.0.0.1", 1234)


def test_blob_roundtrip():
    value = {"arr": np.arange(10000), "meta": "x"}
    blob = serialization.serialize_to_blob(value)
    out, _ = serialization.read_blob(memoryview(blob))
    assert np.array_equal(out["arr"], value["arr"])
    assert out["meta"] == "x"


def test_blob_buffer_alignment():
    arr = np.arange(1000, dtype=np.float64)
    p, bufs, _ = serialization.serialize(arr)
    blob = bytearray(serialization.blob_size(p, bufs))
    serialization.write_blob(memoryview(blob), p, bufs)
    out, _ = serialization.read_blob(memoryview(bytes(blob)))
    assert np.array_equal(out, arr)


def test_closure_function():
    x = 42

    def f(y):
        return x + y

    g = roundtrip(f)
    assert g(1) == 43


def test_inline_roundtrip():
    msg, refs = serialization.serialize_inline([1, np.ones(5)])
    out, _ = serialization.deserialize_inline(msg)
    assert out[0] == 1 and np.array_equal(out[1], np.ones(5))
