"""Per-instance TPU chip assignment (reference: resource instance IDs
scheduling_ids.h:162 / GPU_0-style; TPU manager TPU_VISIBLE_CHIPS
_private/accelerators/tpu.py). Two concurrent TPU workers must never see the
same chip; chips must return to the pool when a lease ends."""

import os

import pytest

import ray_tpu


@pytest.fixture()
def tpu_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=4)
    yield
    ray_tpu.shutdown()


def test_concurrent_actors_get_disjoint_chips(tpu_cluster):
    @ray_tpu.remote(num_tpus=2)
    class Holder:
        def chips(self):
            return os.environ.get("TPU_VISIBLE_CHIPS", "")

    a = Holder.remote()
    b = Holder.remote()
    ca = ray_tpu.get(a.chips.remote(), timeout=60)
    cb = ray_tpu.get(b.chips.remote(), timeout=60)
    assert ca and cb
    sa, sb = set(ca.split(",")), set(cb.split(","))
    assert len(sa) == 2 and len(sb) == 2
    assert not (sa & sb), f"chip overlap: {ca} vs {cb}"
    assert sa | sb == {"0", "1", "2", "3"}
    ray_tpu.kill(a)
    ray_tpu.kill(b)


def test_chips_recycle_after_release(tpu_cluster):
    @ray_tpu.remote(num_tpus=4)
    def all_chips():
        return os.environ.get("TPU_VISIBLE_CHIPS", "")

    first = ray_tpu.get(all_chips.remote(), timeout=60)
    assert set(first.split(",")) == {"0", "1", "2", "3"}
    # lease released after the task; the full pool must be reusable
    second = ray_tpu.get(all_chips.remote(), timeout=60)
    assert set(second.split(",")) == {"0", "1", "2", "3"}


def test_fractional_tpu_shares_pool(tpu_cluster):
    @ray_tpu.remote(num_tpus=0.5)
    def frac():
        return os.environ.get("TPU_VISIBLE_CHIPS", "unset")

    # fractional demand gets no exclusive assignment (shares the node view)
    assert ray_tpu.get(frac.remote(), timeout=60) == "unset"


def test_runtime_context_accelerator_ids(tpu_cluster):
    @ray_tpu.remote(num_tpus=1)
    def ids():
        return ray_tpu.get_runtime_context().get_accelerator_ids()

    out = ray_tpu.get(ids.remote(), timeout=60)
    assert out.get("TPU") in (["0"], [0], ["1"], [1], ["2"], [2], ["3"], [3])
