"""inspect_serializability tests (reference: util/check_serialize.py)."""

import threading

from ray_tpu.util.check_serialize import inspect_serializability

import pytest

pytestmark = pytest.mark.fast  # pure-unit: no cluster boot


def test_serializable_object():
    ok, failures = inspect_serializability({"a": [1, 2], "b": "x"})
    assert ok and not failures


def test_finds_bad_closure():
    lock = threading.Lock()

    def f():
        return lock

    ok, failures = inspect_serializability(f, print_failures=False)
    assert not ok
    assert any(fail.name == "lock" for fail in failures)


def test_finds_bad_attribute():
    class Holder:
        def __init__(self):
            self.fine = 1
            self.bad = threading.Lock()

    ok, failures = inspect_serializability(Holder(), print_failures=False)
    assert not ok
    assert any(fail.name == "bad" and fail.parent == "Holder"
               for fail in failures)
