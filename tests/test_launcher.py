"""Cluster launcher e2e: `up` bootstraps a head + worker as isolated local
processes (provider type `process` — the fake-multinode analogue of
reference autoscaler/_private/fake_multi_node/node_provider.py), a driver
connects and runs work across both nodes, `down` tears everything back
down."""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import launcher


@pytest.fixture
def cluster_yaml(tmp_path):
    port = 47123
    config = f"""
cluster_name: fake-e2e
provider:
  type: process
  state_dir: {tmp_path}/nodes
  head_ip: 127.0.0.1
  worker_ips: ["127.0.0.1"]
setup_commands:
  - echo setup-ran > setup_marker.txt
head_start_command: >-
  ray-tpu start --head --host 127.0.0.1 --port {port}
  --resources '{{"CPU": 2, "head_label": 1}}'
worker_start_command: >-
  ray-tpu start --address=127.0.0.1:{port}
  --resources '{{"CPU": 2, "worker_label": 1}}'
"""
    path = tmp_path / "cluster.yaml"
    path.write_text(config)
    yield str(path), port, tmp_path
    # belt-and-braces teardown if the test failed mid-way
    try:
        launcher.down(str(path))
    except Exception:
        pass


def test_up_run_down(cluster_yaml):
    path, port, tmp_path = cluster_yaml
    info = launcher.up(path)
    assert info["gcs_address"] == f"127.0.0.1:{port}"

    # setup commands ran on every node
    for node in ("node-0", "node-1"):
        marker = tmp_path / "nodes" / "fake-e2e" / node / "setup_marker.txt"
        assert marker.read_text().strip() == "setup-ran", node

    ray_tpu.init(address=info["gcs_address"])
    try:
        # both nodes joined with their labels
        deadline = time.time() + 60
        while time.time() < deadline:
            total = ray_tpu.cluster_resources()
            if total.get("head_label") and total.get("worker_label"):
                break
            time.sleep(1)
        total = ray_tpu.cluster_resources()
        assert total.get("head_label") == 1.0, total
        assert total.get("worker_label") == 1.0, total
        assert total.get("CPU") == 4.0, total

        # run work pinned to each node's label
        @ray_tpu.remote(resources={"worker_label": 0.1})
        def on_worker():
            return "w"

        @ray_tpu.remote(resources={"head_label": 0.1})
        def on_head():
            return "h"

        assert ray_tpu.get([on_worker.remote(), on_head.remote()],
                           timeout=60) == ["w", "h"]
    finally:
        ray_tpu.shutdown()

    launcher.down(path)
    # the GCS is gone: a fresh connect must fail
    from ray_tpu._private.gcs.client import GcsClient

    time.sleep(2)
    with pytest.raises(Exception):
        GcsClient("127.0.0.1", port).call("Ping", {}, timeout=5)


def test_config_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: x\nprovider:\n  type: warp\n")
    with pytest.raises(launcher.LauncherError, match="provider.type"):
        launcher.load_cluster_config(str(bad))
    bad.write_text("provider:\n  type: static\n  head_ip: 1.2.3.4\n")
    with pytest.raises(launcher.LauncherError, match="cluster_name"):
        launcher.load_cluster_config(str(bad))


def test_ssh_runner_command_shape():
    """SSH runner builds a correct command line (no live ssh in CI — we
    intercept subprocess.run)."""
    calls = {}

    def fake_run(cmd, **kw):
        calls["cmd"] = cmd

        class R:
            returncode = 0
            stdout = ""
            stderr = ""

        return R()

    runner = launcher.SSHCommandRunner(
        "10.0.0.9", {"ssh_user": "tpu", "ssh_private_key": "/k"}, "c1"
    )
    orig = launcher.subprocess.run
    launcher.subprocess.run = fake_run
    try:
        runner.run("echo hi", env={"RTPU_HEAD_IP": "10.0.0.2"})
    finally:
        launcher.subprocess.run = orig
    cmd = calls["cmd"]
    assert cmd[0] == "ssh" and "tpu@10.0.0.9" in cmd
    assert any("ControlMaster=auto" in c for c in cmd)
    assert "-i" in cmd and "/k" in cmd
    joined = " ".join(cmd)
    assert "RTPU_HEAD_IP" in joined and "echo hi" in joined
