"""Ray-Client-equivalent tests: drive a cluster from an outside process
(reference: python/ray/util/client/ + tests/test_client.py). The server runs
in a subprocess hosting its own single-node cluster; this test process never
calls ray_tpu.init — everything goes over the client proxy."""

import subprocess
import sys
import time

import pytest

from ray_tpu.util import client as rc


@pytest.fixture(scope="module")
def client_server():
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.util.client.server",
         "--port", "0", "--num-cpus", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "client server listening on" in line:
            port = int(line.strip().rsplit(" ", 1)[1])
            break
        if proc.poll() is not None:
            raise RuntimeError("client server died: " + proc.stdout.read())
    assert port, "server did not come up"
    yield f"127.0.0.1:{port}"
    proc.terminate()
    proc.wait(timeout=20)


@pytest.fixture()
def ctx(client_server):
    c = rc.connect(client_server)
    yield c
    c.disconnect()


def test_ping_and_cluster_info(ctx):
    info = ctx.cluster_info()
    assert info["nodes"] >= 1
    assert info["resources"]["CPU"] >= 4


def test_put_get(ctx):
    ref = ctx.put({"a": [1, 2, 3]})
    assert ctx.get(ref) == {"a": [1, 2, 3]}


def test_task_roundtrip(ctx):
    def double(x):
        return x * 2

    f = ctx.remote(double)
    assert ctx.get(f.remote(21)) == 42
    # refs as args resolve server-side
    r1 = f.remote(10)
    r2 = f.remote(r1)
    assert ctx.get(r2) == 40


def test_task_with_put_arg(ctx):
    ref = ctx.put(5)

    def add(a, b):
        return a + b

    f = ctx.remote(add)
    assert ctx.get(f.remote(ref, 7)) == 12


def test_wait(ctx):
    import time as _t

    def slow(x):
        _t.sleep(x)
        return x

    f = ctx.remote(slow)
    fast, slow_ref = f.remote(0), f.remote(5)
    ready, pending = ctx.wait([fast, slow_ref], num_returns=1, timeout=30)
    assert ready == [fast] and pending == [slow_ref]


def test_actor_lifecycle(ctx):
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

    A = ctx.remote(Counter)
    a = A.remote(10)
    assert ctx.get(a.incr.remote()) == 11
    assert ctx.get(a.incr.remote(5)) == 16
    ctx.kill(a)


def test_named_actor(ctx):
    class Holder:
        def value(self):
            return "named!"

    H = ctx.remote(Holder)
    h = H.options(name="client_named").remote()
    assert ctx.get(h.value.remote()) == "named!"
    h2 = ctx.get_actor("client_named")
    assert ctx.get(h2.value.remote()) == "named!"
    ctx.kill(h)


def test_options_resources(ctx):
    def cpu_heavy():
        return "ok"

    f = ctx.remote(cpu_heavy).options(num_cpus=2)
    assert ctx.get(f.remote()) == "ok"
