"""Control-plane parallelism: sharded RPC reactor, lease-grant batching,
and the plasma-backed submit ring.

Unit layers (ring byte-format, reactor dispatch contract, the FIFO
starvation barrier) run against plain buffers and hand-built NodeManagers;
the live layers boot real clusters and assert the paths end-to-end —
including the fallbacks (ring full → RPC, dead consumer → resubmit
without loss or duplication).
"""

import asyncio
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import chaos as _chaos
from ray_tpu._private.rpc import RpcClient, RpcServer
from ray_tpu._private.submit_ring import (
    HEADER_BYTES,
    RingConsumer,
    RingCorrupt,
    RingProducer,
    ring_bytes,
)


# ------------------------------------------------------------- ring format


@pytest.mark.fast
def test_ring_roundtrip_and_doorbell_transitions():
    buf = bytearray(HEADER_BYTES + 256)
    prod = RingProducer(memoryview(buf), init=True)
    cons = RingConsumer(memoryview(buf))
    # first push of an empty ring reports the empty→non-empty transition
    assert prod.try_push(b"alpha") is True
    # second push while non-empty does not
    assert prod.try_push(b"beta") is False
    assert cons.drain() == [b"alpha", b"beta"]
    assert cons.empty()
    # drained-empty ring transitions again
    assert prod.try_push(b"gamma") is True
    assert cons.drain() == [b"gamma"]


@pytest.mark.fast
def test_ring_wraparound_exact_sequence():
    buf = bytearray(HEADER_BYTES + 128)
    prod = RingProducer(memoryview(buf), init=True)
    cons = RingConsumer(memoryview(buf))
    expected = []
    produced = consumed = 0
    for i in range(200):
        p = (b"%03d" % i) * (1 + i % 7)
        while prod.try_push(p) is None:
            got = cons.drain(max_items=1)
            assert got, "full ring must drain"
            assert got[0] == expected[consumed]
            consumed += 1
        expected.append(p)
        produced += 1
    for g in cons.drain(max_items=1000):
        assert g == expected[consumed]
        consumed += 1
    assert consumed == produced == 200


@pytest.mark.fast
def test_ring_full_returns_none_and_oversize_rejected():
    buf = bytearray(HEADER_BYTES + 128)
    prod = RingProducer(memoryview(buf), init=True)
    # oversize: can never fit
    assert prod.try_push(b"x" * 4096) is None
    pushes = 0
    while prod.try_push(b"y" * 40) is not None:
        pushes += 1
        assert pushes < 100
    assert pushes > 0  # some fit, then clean full signal
    cons = RingConsumer(memoryview(buf))
    assert len(cons.drain()) == pushes


@pytest.mark.fast
def test_ring_closed_flag_and_heartbeat():
    buf = bytearray(HEADER_BYTES + 128)
    prod = RingProducer(memoryview(buf), init=True)
    cons = RingConsumer(memoryview(buf))
    assert not cons.closed()
    assert prod.consumer_beat() == 0.0
    cons.beat(123.5)
    assert prod.consumer_beat() == 123.5
    prod.close()
    assert cons.closed()
    # attaching to garbage fails loudly
    with pytest.raises((RingCorrupt, ValueError)):
        RingConsumer(memoryview(bytearray(HEADER_BYTES + 128)))


@pytest.mark.fast
def test_ring_dead_consumer_fallback_exactly_once():
    """The raylet-restart contract (unit-level): specs the consumer never
    executed are resubmitted via the fallback path; specs that replied are
    not — every task executes exactly once."""
    buf = bytearray(ring_bytes(8))
    prod = RingProducer(memoryview(buf), init=True)
    cons = RingConsumer(memoryview(buf))

    pending = {}  # task_id -> spec (the driver-side _ring_pending analogue)
    executed = []

    for i in range(5):
        tid = b"task-%d" % i
        pending[tid] = {"task_id": tid}
        assert prod.try_push(tid) is not None

    # consumer executes two entries, replies for them, then "dies"
    for tid in cons.drain(max_items=2):
        executed.append(tid)
        pending.pop(tid)  # reply landed driver-side

    # driver detects the stale heartbeat -> fallback resubmit of the rest
    assert prod.consumer_beat() == 0.0  # never beat: dead
    fallback = list(pending.values())
    pending.clear()
    for spec in fallback:
        executed.append(spec["task_id"])  # RPC path executes it

    assert sorted(executed) == sorted(b"task-%d" % i for i in range(5))
    assert len(executed) == len(set(executed))  # no duplicates


# --------------------------------------------------------- sharded reactor


def _run_sharded_server(test_body):
    """Boot an RpcServer with 2 reactor shards inside a private loop and
    run ``test_body(server, port, home_thread_id)`` as a coroutine."""

    async def main():
        server = RpcServer("127.0.0.1", shards=2)
        home_tid = threading.get_ident()
        handler_tids = []

        async def echo(payload):
            handler_tids.append(threading.get_ident())
            return {"echo": payload["x"]}

        server.register("Echo", echo)
        port = await server.start(0)
        assert server.num_shards == 2
        try:
            await test_body(server, port, home_tid, handler_tids)
        finally:
            await server.stop()

    asyncio.run(main())


@pytest.mark.fast
def test_sharded_reactor_serves_many_connections():
    """Connections land on different shard loops; handlers still run on
    the HOME loop (the dispatch contract protecting shared state), and
    every response routes back on the right connection."""

    async def body(server, port, home_tid, handler_tids):
        clients = []
        # 4 connections round-robin over 2 shards: at least one serves on
        # a non-home thread
        for _ in range(4):
            c = RpcClient("127.0.0.1", port)
            await c.connect()
            clients.append(c)
        results = await asyncio.gather(*(
            c.call("Echo", {"x": i}, timeout=10)
            for i, c in enumerate(clients)
            for _ in range(5)
        ))
        assert [r["echo"] for r in results] == [i for i in range(4)
                                                for _ in range(5)]
        assert set(handler_tids) == {home_tid}  # home-loop dispatch held
        for c in clients:
            await c.close()

    _run_sharded_server(body)


@pytest.mark.fast
def test_set_shard_safe_rejects_unresolved_names():
    """A typo'd set_shard_safe name used to silently keep the handler
    hopping home — correct but quietly defeating the optimization. Now it
    raises at registration, and the lint plane's shard-safe-unresolved
    rule catches the literal form before a cluster even boots."""
    server = RpcServer("127.0.0.1")

    async def ping(payload):
        return {"ok": True}

    server.register("Ping", ping)
    server.set_shard_safe({"Ping"})  # resolves: fine
    with pytest.raises(ValueError, match="PingTypo"):
        server.set_shard_safe({"PingTypo"})
    # the failed call must not have poisoned the good registration
    assert "Ping" in server._shard_safe
    assert "PingTypo" not in server._shard_safe


@pytest.mark.fast
def test_shard_safe_handler_runs_on_shard_thread():
    async def main():
        server = RpcServer("127.0.0.1", shards=2)
        home_tid = threading.get_ident()
        tids = []

        async def probe(payload):
            tids.append(threading.get_ident())
            return {"ok": True}

        server.register("Probe", probe)
        server.set_shard_safe({"Probe"})
        port = await server.start(0)
        try:
            # two connections: one on the home loop (shard 0), one on a
            # shard thread — the shard-safe handler runs in place on both
            for _ in range(2):
                c = RpcClient("127.0.0.1", port)
                await c.connect()
                assert (await c.call("Probe", {}, timeout=10))["ok"]
                await c.close()
            assert home_tid in tids
            assert any(t != home_tid for t in tids)
        finally:
            await server.stop()

    asyncio.run(main())


@pytest.mark.fast
def test_sharded_reactor_errors_oob_and_notify():
    """RemoteError propagation, OOB sinks, and notifies all work from a
    shard loop (connection #2 of 2 shards is off-home)."""

    async def main():
        server = RpcServer("127.0.0.1", shards=2)
        landed = {}
        notified = asyncio.Event()
        home_loop = asyncio.get_running_loop()

        async def boom(payload):
            raise ValueError("kaboom")

        async def land(payload):
            return {"oob": payload.get("_oob")}

        async def note(payload):
            home_loop  # noqa: B018 — handler runs here thanks to the hop
            notified.set()

        def sink(payload, nbytes):
            buf = bytearray(nbytes)
            landed["buf"] = buf
            return memoryview(buf), None

        server.register("Boom", boom)
        server.register("Land", land)
        server.register("Note", note)
        server.set_oob_sink("Land", sink)
        port = await server.start(0)
        try:
            # burn connection 1 (home shard), test on connection 2 (shard)
            c0 = RpcClient("127.0.0.1", port)
            await c0.connect()
            c = RpcClient("127.0.0.1", port)
            await c.connect()
            from ray_tpu._private.rpc import RemoteError

            with pytest.raises(RemoteError) as ei:
                await c.call("Boom", {}, timeout=10)
            assert isinstance(ei.value.exception, ValueError)
            r = await c.call("Land", {}, oob=b"payload!", timeout=10)
            assert r["oob"] == 8 and bytes(landed["buf"]) == b"payload!"
            await c.notify("Note", {})
            await asyncio.wait_for(notified.wait(), 10)
            await c.close()
            await c0.close()
        finally:
            await server.stop()

    asyncio.run(main())


@pytest.mark.fast
def test_sharded_reactor_chaos_recv_seam():
    """The chaos rpc.recv seam fires per-shard: a drop rule swallows the
    request on a shard connection exactly like on the home loop."""

    async def main():
        _chaos.load_plan({"seed": 1, "rules": [
            {"site": "rpc.recv", "action": "drop", "method": "Flaky",
             "count": 1}]})
        try:
            server = RpcServer("127.0.0.1", shards=2)
            calls = []

            async def flaky(payload):
                calls.append(1)
                return {"ok": True}

            server.register("Flaky", flaky)
            port = await server.start(0)
            try:
                c0 = RpcClient("127.0.0.1", port)
                await c0.connect()
                c = RpcClient("127.0.0.1", port)  # lands on shard 1
                await c.connect()
                with pytest.raises(asyncio.TimeoutError):
                    await c.call("Flaky", {}, timeout=0.5)
                # rule count exhausted: next call goes through
                r = await c.call("Flaky", {}, timeout=10)
                assert r["ok"] and calls == [1]
                await c.close()
                await c0.close()
            finally:
                await server.stop()
        finally:
            _chaos.clear()

    asyncio.run(main())


@pytest.mark.fast
def test_upgrade_flush_and_adopt_on_shard():
    """The direct-channel upgrade handshake works from a shard loop, and
    the response is fully flushed (no busy-wait: _flush_transport rides
    the transport's flow-control signal) before the socket is adopted."""

    async def main():
        server = RpcServer("127.0.0.1", shards=2)
        adopted = {}

        def hook(payload):
            def adopt(sock):
                adopted["sock"] = sock

                def serve():
                    # trivial protocol on the adopted blocking socket (the
                    # real direct channel hands it to a thread the same way)
                    data = sock.recv(5)
                    sock.sendall(data.upper())

                threading.Thread(target=serve, daemon=True).start()

            return {"ok": True, "blob": b"z" * 200_000}, adopt

        server.set_upgrade_hook("Upgrade", hook)
        port = await server.start(0)
        try:
            c0 = RpcClient("127.0.0.1", port)
            await c0.connect()
            c = RpcClient("127.0.0.1", port)  # shard connection
            await c.connect()
            r = await c.call("Upgrade", {}, timeout=10)
            # the large response survived the pre-abort flush intact
            assert r["ok"] and len(r["blob"]) == 200_000
            # the connection is now a raw socket owned by the adopter —
            # talk over a blocking dup of the client fd off-loop
            raw = c._writer.get_extra_info("socket").dup()
            raw.setblocking(True)
            loop = asyncio.get_running_loop()

            def ping():
                raw.sendall(b"hello")
                return raw.recv(5)

            reply = await asyncio.wait_for(
                loop.run_in_executor(None, ping), 10)
            assert reply == b"HELLO"
            assert "sock" in adopted
            raw.close()
            await c0.close()
            try:
                await c.close()
            except Exception:
                pass
        finally:
            await server.stop()

    asyncio.run(main())


# --------------------------------------------- lease-grant batching (unit)


def _mini_node_manager(cpus=4.0):
    """A NodeManager skeleton with just the lease-pass state (no sockets,
    no plasma) — enough to drive _lease_grant_pass/_kick_waiters."""
    from ray_tpu._private.raylet.main import NodeManager
    from ray_tpu._private.raylet.resources import ResourceSet

    nm = NodeManager.__new__(NodeManager)
    nm.total = ResourceSet({"CPU": cpus})
    nm.available = ResourceSet({"CPU": cpus})
    nm.bundles = {}
    nm._resources_dirty = False
    nm._lease_waiters = []
    nm._lease_pass_scheduled = False
    nm._starve_limit = 3  # small so tests exercise the barrier quickly
    nm._rings = {}
    nm._ring_event = None
    return nm


def _waiter(res, strat=None):
    return {"event": asyncio.Event(), "res": dict(res),
            "strat": strat or {}, "skips": 0}


@pytest.mark.fast
def test_lease_pass_grants_fifo_and_batches():
    nm = _mini_node_manager(cpus=2.0)
    w1, w2, w3 = (_waiter({"CPU": 1}) for _ in range(3))
    nm._lease_waiters = [w1, w2, w3]
    nm._lease_grant_pass()
    # one pass granted the two that fit, FIFO order, left the third queued
    assert w1["event"].is_set() and "grant" in w1
    assert w2["event"].is_set() and "grant" in w2
    assert not w3["event"].is_set()
    assert nm._lease_waiters == [w3]
    assert nm.available.to_dict().get("CPU", 0) == 0


@pytest.mark.fast
def test_lease_pass_starvation_barrier():
    """A large waiter skipped `lease_starvation_passes` times becomes a
    FIFO barrier: later small waiters stop leapfrogging it, and fresh
    requests are told to queue behind it."""
    nm = _mini_node_manager(cpus=2.0)
    big = _waiter({"CPU": 2})
    nm.available.acquire(__import__(
        "ray_tpu._private.raylet.resources",
        fromlist=["ResourceSet"]).ResourceSet({"CPU": 1}))  # 1 of 2 busy
    nm._lease_waiters = [big]
    # passes 1..3: big can't fit (needs 2, 1 available) -> skips accumulate
    for expected_skips in (1, 2, 3):
        nm._lease_grant_pass()
        assert not big["event"].is_set()
        assert big["skips"] == expected_skips
    # big is now starving: a later small waiter may NOT leapfrog it even
    # though 1 CPU is free
    small = _waiter({"CPU": 1})
    nm._lease_waiters.append(small)
    nm._lease_grant_pass()
    assert not small["event"].is_set(), "small leapfrogged a starving waiter"
    # ...and fresh small requests are diverted into the queue too
    assert nm._blocked_by_starving({"CPU": 1}, {})
    # disjoint resources are unaffected by the barrier
    assert not nm._blocked_by_starving({"TPU": 1}, {})
    # the blocking release arrives: the very next pass serves BIG first
    nm.available.release(__import__(
        "ray_tpu._private.raylet.resources",
        fromlist=["ResourceSet"]).ResourceSet({"CPU": 1}))
    nm._lease_grant_pass()
    assert big["event"].is_set() and "grant" in big
    assert not small["event"].is_set()  # nothing left after the big grant


@pytest.mark.fast
def test_lease_waiter_abandon_returns_raced_grant():
    nm = _mini_node_manager(cpus=1.0)
    w = _waiter({"CPU": 1})
    nm._lease_waiters = [w]
    nm._lease_grant_pass()
    assert w["event"].is_set() and "grant" in w
    # the handler timed out before consuming the grant: abandon returns it

    async def drive():
        nm._waiter_abandon(w)

    asyncio.run(drive())
    assert nm.available.to_dict().get("CPU") == 1.0


@pytest.mark.fast
def test_kick_waiters_coalesces_into_one_pass():
    nm = _mini_node_manager(cpus=4.0)
    passes = []
    orig = nm._lease_grant_pass
    nm._lease_grant_pass = lambda: (passes.append(1), orig())

    async def drive():
        nm._lease_waiters = [_waiter({"CPU": 1}) for _ in range(3)]
        # K releases in one tick -> ONE scheduled pass
        for _ in range(5):
            nm._kick_waiters()
        await asyncio.sleep(0)  # let call_soon run

    asyncio.run(drive())
    assert sum(passes) == 1
    assert all(w["event"].is_set() for w in nm._lease_waiters) or \
        not nm._lease_waiters


# ------------------------------------------------------------- live layers


@pytest.fixture
def fresh_cluster():
    yield
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


def test_submit_ring_live_end_to_end(fresh_cluster):
    """Default config: eligible tiny tasks ride the ring; results land via
    the batched reply notify; nothing leaks in the pending table."""

    @ray_tpu.remote
    def f(x):
        return x * 2

    ray_tpu.init(num_cpus=4)
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker()
    assert ray_tpu.get([f.remote(i) for i in range(40)]) == \
        [2 * i for i in range(40)]
    deadline = time.time() + 10
    while w._ring is None and time.time() < deadline:
        time.sleep(0.05)
    assert w._ring is not None, "submit ring never attached"
    assert ray_tpu.get([f.remote(i) for i in range(400)]) == \
        [2 * i for i in range(400)]
    assert w._ring_submitted > 0, "no task rode the ring"
    assert not w._ring_pending, "ring reply leak"
    assert not w._ring_dead


def test_submit_ring_full_falls_back_to_rpc(fresh_cluster):
    """A deliberately tiny ring forces constant ring-full fallbacks; every
    task still completes exactly once with correct results."""
    os.environ["RTPU_submit_ring_slots"] = "1"  # ~1 KiB: a couple entries
    try:
        @ray_tpu.remote
        def f(x):
            return x + 7

        ray_tpu.init(num_cpus=4)
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        assert ray_tpu.get([f.remote(i) for i in range(300)]) == \
            [i + 7 for i in range(300)]
        assert not w._ring_pending
    finally:
        os.environ.pop("RTPU_submit_ring_slots", None)


def test_submit_ring_disabled_via_flag(fresh_cluster):
    os.environ["RTPU_submit_ring_slots"] = "0"
    try:
        @ray_tpu.remote
        def f(x):
            return x

        ray_tpu.init(num_cpus=2)
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        assert ray_tpu.get([f.remote(i) for i in range(50)]) == list(range(50))
        assert w._ring is None and w._ring_submitted == 0
    finally:
        os.environ.pop("RTPU_submit_ring_slots", None)


def test_large_lease_not_starved_by_small_stream(fresh_cluster):
    """Regression (satellite): a CPU-2 task queued behind a continuous
    stream of CPU-1 tasks that fit first must still get scheduled — the
    batched pass's starvation barrier guarantees it."""
    os.environ["RTPU_lease_starvation_passes"] = "4"
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote(num_cpus=1)
        def small():
            time.sleep(0.05)
            return 1

        @ray_tpu.remote(num_cpus=2)
        def big():
            return "BIG"

        # keep both slots churning with small tasks...
        stream = [small.remote() for _ in range(80)]
        time.sleep(0.1)
        # ...then ask for the whole node
        big_ref = big.remote()
        more = [small.remote() for _ in range(80)]
        assert ray_tpu.get(big_ref, timeout=60) == "BIG"
        assert sum(ray_tpu.get(stream + more, timeout=120)) == 160
    finally:
        os.environ.pop("RTPU_lease_starvation_passes", None)


def test_cluster_smoke_with_two_reactor_shards(fresh_cluster):
    """Whole-cluster smoke with RTPU_rpc_reactor_shards=2 in every process
    (driver, GCS, raylet, workers): tasks, actors, plasma round-trips and
    the submit ring all function across shard boundaries."""
    os.environ["RTPU_rpc_reactor_shards"] = "2"
    try:
        import numpy as np

        @ray_tpu.remote
        def f(x):
            return x + 1

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        ray_tpu.init(num_cpus=4)
        from ray_tpu._private.worker import get_global_worker

        assert get_global_worker().server.num_shards == 2
        assert ray_tpu.get([f.remote(i) for i in range(200)]) == \
            list(range(1, 201))
        c = Counter.remote()
        assert ray_tpu.get([c.bump.remote() for _ in range(30)])[-1] == 30
        arr = np.arange(1 << 18)
        assert (ray_tpu.get(ray_tpu.put(arr)) == arr).all()
    finally:
        os.environ.pop("RTPU_rpc_reactor_shards", None)
