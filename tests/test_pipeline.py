"""Pipeline-parallel train step (pp mesh axis): GPipe schedule correctness
vs the sequential stack, and an end-to-end sharded training step.

Reference analogue: the compiled-DAG pipeline tests (python/ray/dag/tests/);
here the within-slice pipeline is a mesh axis + ppermute schedule, so the
correctness bar is exact equivalence with the unpipelined forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.gpt2 import Block, GPT2Config
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.parallel.pipeline import PipelineTrainStep, pipeline_apply

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >=4 virtual devices"
)


def _cfg(**kw):
    base = dict(
        vocab_size=128, block_size=32, n_layer=4, n_head=2, n_embd=32,
        dtype=jnp.float32, use_flash_attention=False,
    )
    base.update(kw)
    return GPT2Config(**base)


def test_pipeline_forward_matches_sequential():
    cfg = _cfg()
    mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    ts = PipelineTrainStep(cfg, mesh, num_microbatches=4)
    state = ts.init(jax.random.PRNGKey(0))
    idx = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, cfg.block_size)),
        dtype=jnp.int32,
    )

    logits_pp = ts.forward(state["params"], ts.shard_batch({"idx": idx})["idx"])

    # sequential reference: same params, plain python loop over the stack
    params = jax.device_get(state["params"])
    block = Block(cfg)
    h = (
        params["wte"][np.asarray(idx)]
        + params["wpe"][np.arange(cfg.block_size)][None]
    ).astype(np.float32)
    h = jnp.asarray(h)
    for i in range(cfg.n_layer):
        layer = jax.tree.map(lambda x: x[i], params["blocks"])
        h = block.apply({"params": layer}, h)
    mean = h.mean(-1, keepdims=True)
    var = ((h - mean) ** 2).mean(-1, keepdims=True)
    h = (h - mean) * jax.lax.rsqrt(var + 1e-5)
    h = h * params["ln_f"]["scale"] + params["ln_f"]["bias"]
    logits_ref = h.astype(jnp.float32) @ params["wte"].T

    err = jnp.abs(logits_pp - logits_ref).max()
    assert err < 2e-4, f"pipeline diverges from sequential: {err}"


def test_pipeline_train_step_learns():
    cfg = _cfg()
    mesh = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    ts = PipelineTrainStep(cfg, mesh, num_microbatches=2, learning_rate=1e-2)
    state = ts.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    idx = rng.randint(0, cfg.vocab_size, (4, cfg.block_size)).astype(np.int32)
    batch = ts.shard_batch(
        {"idx": jnp.asarray(idx), "targets": jnp.asarray(np.roll(idx, -1, 1))}
    )
    losses = []
    for _ in range(5):
        state, metrics = ts.step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
    # block grads/params stay sharded over pp
    stacked = state["params"]["blocks"]
    leaf = jax.tree.leaves(stacked)[0]
    assert "pp" in str(leaf.sharding.spec)


def test_pipeline_apply_pp4():
    """pp=4 with a trivially-checkable block (x + w)."""
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    L, B, T, D = 8, 4, 2, 4
    w = jnp.arange(L, dtype=jnp.float32).reshape(L, 1, 1, 1)

    def add_block(p, x):
        return x + p

    h = jnp.ones((B, T, D), jnp.float32)
    out = pipeline_apply(mesh, lambda p, x: x + p, w, h, num_micro=4)
    expected = 1.0 + sum(range(L))
    assert jnp.allclose(out, expected), (out.ravel()[0], expected)
