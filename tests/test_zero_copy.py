"""Zero-copy data plane: single-copy put, out-of-band RPC frames, and
copy-free chunked transfer.

The acceptance contract is structural, not timing-based: the put path and
the chunk send path must never materialize an out-of-band buffer as Python
bytes — asserted here by buffer identity (np.shares_memory) and by the
"_oob" landed-in-place markers of the RPC layer. Timing lives in
microbench.py (and the abbreviated smoke at the bottom of this file).
"""

import asyncio
import hashlib

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import rpc as rpc_mod
from ray_tpu._private import serialization
from ray_tpu._private.rpc import (
    OobPayload,
    RpcClient,
    RpcServer,
    _pack_oob,
)


# --------------------------------------------------------------- rpc frames


@pytest.mark.fast
def test_pack_oob_no_copy():
    """The frame builder returns the caller's buffer view itself — the bulk
    bytes are never copied into the packed header."""
    arr = np.arange(1_000_000, dtype=np.uint8)
    view = memoryview(arr)
    hdr, mv = _pack_oob(rpc_mod.MSG_REQUEST_OOB, 7, "ReceiveChunk",
                        {"offset": 0}, view)
    assert mv is view  # identity: zero copies on the send side
    assert len(hdr) < 100  # header is just the msgpack envelope
    # a bytes-like that is not a memoryview gets wrapped, not copied
    buf = bytearray(b"x" * 4096)
    hdr2, mv2 = _pack_oob(rpc_mod.MSG_RESPONSE_OOB, 1, None, {}, buf)
    assert isinstance(mv2, memoryview) and mv2.obj is buf
    assert np.shares_memory(np.frombuffer(mv2, dtype=np.uint8),
                            np.frombuffer(buf, dtype=np.uint8))


@pytest.mark.fast
def test_oob_request_lands_in_sink_buffer():
    """An OOB request's payload streams from the socket straight into the
    buffer the server's sink provides; the handler sees only the int
    byte-count marker (proof nothing was buffered on the heap)."""

    async def main():
        landing = bytearray(1 << 20)
        seen = {}
        done_calls = []

        def sink(payload, nbytes):
            seen["sink"] = (dict(payload), nbytes)
            return (memoryview(landing)[payload["offset"]:
                                        payload["offset"] + nbytes],
                    lambda ok: done_calls.append(ok))

        async def handler(payload):
            seen["handler"] = payload
            return {"ok": True, "oob_was": payload.get("_oob")}

        server = RpcServer("127.0.0.1")
        server.register("Land", handler)
        server.set_oob_sink("Land", sink)
        port = await server.start(0)
        client = RpcClient("127.0.0.1", port)
        await client.connect()

        data = np.arange(512 * 1024, dtype=np.uint8)
        r = await client.call("Land", {"offset": 4096},
                              oob=memoryview(data), timeout=10)
        assert r["ok"] and r["oob_was"] == data.nbytes
        assert seen["handler"]["_oob"] == data.nbytes  # int marker: landed
        assert done_calls == [True]
        assert bytes(landing[4096:4096 + data.nbytes]) == data.tobytes()

        # no sink match (bad offset) -> payload buffers into a bytearray,
        # stream stays framed, handler still runs
        def sink_reject(payload, nbytes):
            return None

        server.set_oob_sink("Land", sink_reject)
        r = await client.call("Land", {"offset": 0},
                              oob=b"hello world", timeout=10)
        assert bytes(r["oob_was"]) == b"hello world"

        await client.close()
        await server.stop()

    asyncio.run(main())


@pytest.mark.fast
def test_oob_response_lands_in_client_buffer():
    """A handler returning OobPayload streams its buffer raw; the client's
    oob_dest receives it in place (the pull path's chunk landing)."""

    async def main():
        src = np.arange(256 * 1024, dtype=np.uint8)
        released = []

        async def handler(payload):
            return OobPayload({"found": True}, memoryview(src),
                              release=lambda: released.append(True))

        server = RpcServer("127.0.0.1")
        server.register("Fetch", handler)
        port = await server.start(0)
        client = RpcClient("127.0.0.1", port)
        await client.connect()

        dest = bytearray(src.nbytes)
        r = await client.call("Fetch", {}, timeout=10,
                              oob_dest=memoryview(dest))
        assert r["found"] and r["_oob"] == src.nbytes  # landed in dest
        assert bytes(dest) == src.tobytes()
        assert released == [True]  # handler's pin released after flush

        # without oob_dest the payload still arrives (buffered fallback)
        r = await client.call("Fetch", {}, timeout=10)
        assert bytes(r["_oob"]) == src.tobytes()

        # interleave OOB with plain requests on one connection: framing holds
        async def plain(payload):
            return {"echo": payload["x"]}

        server.register("Plain", plain)
        dest2 = bytearray(src.nbytes)
        results = await asyncio.gather(
            client.call("Fetch", {}, timeout=10, oob_dest=memoryview(dest2)),
            client.call("Plain", {"x": 42}, timeout=10),
            client.call("Plain", {"x": 43}, timeout=10),
        )
        assert results[0]["_oob"] == src.nbytes
        assert bytes(dest2) == src.tobytes()
        assert [results[1]["echo"], results[2]["echo"]] == [42, 43]

        await client.close()
        await server.stop()

    asyncio.run(main())


@pytest.mark.fast
def test_oob_zero_length_payload():
    """Zero-byte OOB payloads (empty tail chunk edge) keep the stream
    framed on both directions."""

    async def main():
        async def handler(payload):
            return OobPayload({"n": payload["_oob"]}, b"")

        server = RpcServer("127.0.0.1")
        server.register("Zero", handler)
        port = await server.start(0)
        client = RpcClient("127.0.0.1", port)
        await client.connect()
        r = await client.call("Zero", {}, oob=b"", timeout=10)
        assert bytes(r["n"]) == b"" and bytes(r["_oob"]) == b""
        r = await client.call("Zero", {}, oob=b"", timeout=10)
        assert bytes(r["n"]) == b""
        await client.close()
        await server.stop()

    asyncio.run(main())


# ---------------------------------------------------------------- put path


def test_put_streams_raw_buffers_into_plasma(ray_start_regular):
    """ray.put of a plasma-bound array hands write_blob the RAW protocol-5
    buffer aliasing the user's array — buffer identity, not timing, is the
    zero-copy proof (a reintroduced bytes() breaks shares_memory)."""
    captured = []
    orig = serialization.write_blob

    def spy(dest, pickle_bytes, buffers):
        captured.append(list(buffers))
        return orig(dest, pickle_bytes, buffers)

    arr = np.arange(2 * 1024 * 1024 // 8, dtype=np.float64)  # 2 MiB
    arr_bytes = arr.view(np.uint8)
    serialization.write_blob, write_blob = spy, orig
    try:
        ref = ray_tpu.put(arr)
    finally:
        serialization.write_blob = write_blob
    assert len(captured) == 1 and len(captured[0]) == 1
    buf = captured[0][0]
    assert not isinstance(buf, (bytes, bytearray))
    alias = np.frombuffer(memoryview(buf).cast("B"), dtype=np.uint8)
    assert np.shares_memory(alias, arr_bytes)
    # and the stored object reads back intact (zero-copy view of plasma)
    out = ray_tpu.get(ref)
    assert np.array_equal(out, arr)


def test_large_task_return_streams_raw_buffers(ray_start_regular):
    """Large task returns ride the same single-copy path: value -> plasma,
    no intermediate bytes of the array on the worker heap."""

    @ray_tpu.remote
    def make():
        return np.full(1_000_000, 3.25)  # 8 MB -> plasma

    out = ray_tpu.get(make.remote())
    assert out.shape == (1_000_000,) and float(out[0]) == 3.25
    # the value aliases the store (zero-copy get): read-only-safe check
    # that its deep base is a memoryview over shared memory, not a heap copy
    base = out
    while getattr(base, "base", None) is not None:
        base = base.base
    assert isinstance(base, memoryview)


def test_zero_copy_get_pin_survives_store_churn(ray_start_regular):
    """A value read zero-copy from plasma stays intact while later puts
    evict/spill around it — the pin must ride the value's actual buffer
    retention chain (regression: the finalizer used to sit on the
    PickleBuffer, which numpy drops at unpickle time, so the store could
    recycle pinned memory under churn)."""
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 255, size=2 * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    # churn the store with ~3x its working set of unrelated objects
    for i in range(24):
        ray_tpu.put(rng.integers(0, 255, size=8 * 1024 * 1024, dtype=np.uint8))
    assert np.array_equal(out, arr)


# --------------------------------------------- two-raylet chunked transfer


@pytest.fixture
def two_nodes_small_chunks(monkeypatch):
    """Head + one worker node with a 64 KiB transfer chunk so moderate
    objects span many chunks (chunk-boundary coverage without big data)."""
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RTPU_object_manager_chunk_size", str(64 * 1024))
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 1, "n0": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_push_integrity_across_chunk_boundaries(two_nodes_small_chunks):
    """PushObject over out-of-band frames: byte-for-byte integrity of an
    object spanning many chunks with a ragged tail (off-by-one at any
    chunk boundary, or a mislanded offset, flips the digest)."""
    from ray_tpu._private.worker import get_global_worker

    chunk = 64 * 1024
    n = 17 * chunk + 4321  # 17 full chunks + ragged tail
    data = (np.arange(n, dtype=np.int64) % 251).astype(np.uint8)
    ref = ray_tpu.put(data)
    want = hashlib.sha256(data.tobytes()).hexdigest()

    worker = get_global_worker()
    oid = ref.object_id()

    async def push():
        nodes = await worker.gcs_aio.get_all_node_info()
        by_res = {}
        for node in nodes:
            by_res[node["node_id"]] = node
        src = worker.node_id.binary()
        dst = next(nid for nid in by_res if nid != src)
        client = await worker.pool.get(
            by_res[src]["ip"], by_res[src]["raylet_port"]
        )
        return dst, await client.call(
            "PushObject",
            {"object_id": oid.binary(), "target": dst,
             "owner_addr": list(worker.address)},
            timeout=120,
        )

    dst, reply = worker.io.run(push())
    assert reply.get("ok"), reply

    # read it back ON the target node (no further transfer: n0 resource)
    @ray_tpu.remote(resources={"n0": 1})
    def digest(v):
        import hashlib as _h

        return _h.sha256(np.asarray(v).tobytes()).hexdigest()

    assert ray_tpu.get(digest.remote(ref), timeout=120) == want


def test_pull_integrity_across_chunk_boundaries(two_nodes_small_chunks):
    """The pull path (FetchChunk out-of-band responses landing straight in
    the puller's plasma buffer) reassembles a multi-chunk object exactly."""
    chunk = 64 * 1024
    n = 9 * chunk + 1  # 9 chunks + 1-byte tail: worst-case ragged boundary
    rng = np.random.default_rng(11)
    data = rng.integers(0, 255, size=n, dtype=np.uint8)
    ref = ray_tpu.put(data)
    want = hashlib.sha256(data.tobytes()).hexdigest()

    @ray_tpu.remote(resources={"n0": 1})
    def digest(v):
        import hashlib as _h

        return _h.sha256(np.asarray(v).tobytes()).hexdigest()

    # dependency resolution on n0 pulls the object chunk-by-chunk
    assert ray_tpu.get(digest.remote(ref), timeout=120) == want


# ------------------------------------------------------- bandwidth smoke


def test_put_bandwidth_smoke(ray_start_regular):
    """Abbreviated put-bandwidth rep (tier-1-safe): one warm put plus a
    short timed run. The floor is deliberately loose — the structural
    zero-copy assertions above catch copy regressions deterministically;
    this only trips on a catastrophic slowdown of the fast path."""
    import time

    big = np.zeros(64 * 1024 * 1024 // 8, dtype=np.float64)  # 64 MiB
    gib = big.nbytes / (1 << 30)
    ray_tpu.put(big)  # warm: page-faults the store region once
    count = 0
    t0 = time.perf_counter()
    while True:
        ray_tpu.put(big)
        count += 1
        dt = time.perf_counter() - t0
        if dt >= 1.0 or count >= 64:
            break
    rate = count * gib / dt
    # this box: ~5-6 GiB/s zero-copy, ~1.4 GiB/s with the old double copy
    assert rate > 0.2, f"put bandwidth collapsed: {rate:.2f} GiB/s"
