"""Object push + broadcast fan-out (reference: object_manager.cc:339 Push,
push_manager.h, release/benchmarks object_store broadcast)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.broadcast import broadcast_object


@pytest.fixture
def three_nodes():
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    for i in range(3):
        cluster.add_node(resources={"CPU": 1, f"n{i}": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_broadcast_tree_fanout(three_nodes):
    """Broadcast uses >=2 distinct sources (tree fan-out), not N pushes
    from the primary, and every node ends up holding a copy."""
    data = np.arange(2_000_000, dtype=np.float64)  # 16 MB -> plasma
    ref = ray_tpu.put(data)

    stats = broadcast_object(ref)
    assert len(stats["nodes"]) == 4  # head + 3 workers
    sources = {s for s, _ in stats["transfers"]}
    assert len(stats["transfers"]) == 3  # N-1 transfers total
    assert len(sources) >= 2, (
        f"broadcast used a single source: {stats['transfers']}"
    )
    assert stats["rounds"] <= 2  # ceil(log2(4))

    # every node can now read the value locally (no further transfer):
    # schedule a reader on each worker node via its private resource
    for i in range(3):
        @ray_tpu.remote(resources={f"n{i}": 1})
        def readback(v):
            import numpy as _np

            return float(_np.asarray(v).sum())

        # the ref arg resolves node-locally (a copy is already there)
        assert ray_tpu.get(readback.remote(ref)) == float(data.sum())


def test_hot_object_pull_spreads_sources(three_nodes):
    """Concurrent pullers of a hot object spread over registered holders
    (shuffled source selection) instead of all hitting the primary."""
    data = np.ones(1_000_000, dtype=np.float64)  # 8 MB
    ref = ray_tpu.put(data)

    # seed one extra copy via push, then let the remaining nodes pull
    stats = broadcast_object(ref)
    assert len(stats["nodes"]) == 4

    @ray_tpu.remote
    def reader(v):
        return float(v.sum())

    out = ray_tpu.get([reader.remote(ref) for _ in range(6)])
    assert out == [float(data.sum())] * 6


def test_push_object_rpc_direct(three_nodes):
    """A single PushObject RPC moves a spilled-or-resident object to an
    explicit target node."""
    from ray_tpu._private.worker import get_global_worker

    data = np.full(500_000, 7.0)
    ref = ray_tpu.put(data)
    worker = get_global_worker()
    oid = ref.object_id()

    nodes = worker.gcs.get_all_node_info()
    me = worker.node_id.binary()
    target = next(n for n in nodes if n["node_id"] != me)
    holder = next(n for n in nodes if n["node_id"] == me)

    async def push():
        client = await worker.pool.get(
            holder["ip"], holder["raylet_port"]
        )
        return await client.call(
            "PushObject",
            {"object_id": oid.binary(), "target": target["node_id"],
             "owner_addr": list(worker.address)},
            timeout=60,
        )

    r = worker.io.run(push())
    assert r.get("ok"), r
    # AddObjectLocation arrives as a fire-and-forget notify: poll briefly
    import time

    deadline = time.time() + 10
    while True:
        entry = worker.memory_store.get_if_exists(oid)
        locs = set(entry.locations) | worker._object_locations.get(
            oid.binary(), set()
        )
        if target["node_id"] in locs:
            break
        assert time.time() < deadline, f"location never registered: {locs}"
        time.sleep(0.1)
