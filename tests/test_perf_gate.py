"""Perf regression plane: comparator + ledger + storm detection + analysis.

Contracts under test:
  - a synthetically injected 2x slowdown in one microbench metric trips the
    gate; in-band jitter (inside the documented noise bands) passes;
  - the ledger round-trips: append -> load_baseline/load_history -> compare;
  - `ray-tpu perf compare` (the CI A/B path) accepts both microbench.v1 and
    the legacy plain {metric: value} format and exits 1 on regression;
  - the StepRecorder flags a post-warmup jit-compile storm and the watchdog
    promotes it to a jit_cache_miss_storm GCS incident;
  - incident auto-analysis extracts top stacks / compile share / scheduling
    delay from an attached merged-profile capture and writes a
    human-readable summary into the incident record;
  - bench.py with the TPU tunnel unreachable still emits one valid JSON
    result line tagged "plane": "cpu";
  - tier-1 smoke: `ray-tpu perf check --only ... --quick` runs the real
    microbench subset end-to-end and appends to the ledger.
"""

import json
import os

import pytest

from ray_tpu._private import perf_analysis as pa
from ray_tpu._private import perf_gate as pg


# ------------------------------------------------------------- comparator


@pytest.mark.fast
def test_synthetic_regression_trips_gate():
    base = {"single_client_tasks_sync": 1000.0}
    cur = {"single_client_tasks_sync": 500.0}  # injected 2x slowdown
    report = pg.compare(base, cur, base_reps=3, cur_reps=3)
    assert report["status"] == "fail"
    assert report["regressions"] == ["single_client_tasks_sync"]
    row = report["metrics"]["single_client_tasks_sync"]
    assert row["status"] == "regression" and row["ratio"] == 0.5
    # even the widest single-rep band catches a 2x collapse
    report1 = pg.compare(base, cur, base_reps=1, cur_reps=1)
    assert report1["status"] == "fail"


@pytest.mark.fast
def test_in_band_jitter_passes():
    base = {"single_client_tasks_sync": 1000.0,
            "multi_client_tasks_async": 3000.0}
    # -20% on a 25%-band metric, -30% on a 35%-band (multi-process) metric
    cur = {"single_client_tasks_sync": 800.0,
           "multi_client_tasks_async": 2100.0}
    report = pg.compare(base, cur, base_reps=3, cur_reps=3)
    assert report["status"] == "pass", report
    assert not report["regressions"]
    # the same -30% on the tighter default band IS a regression: the bands
    # are per-metric, not one global number
    report2 = pg.compare({"single_client_tasks_sync": 1000.0},
                         {"single_client_tasks_sync": 700.0},
                         base_reps=3, cur_reps=3)
    assert report2["status"] == "fail"


@pytest.mark.fast
def test_band_selection_and_statuses():
    # band widens when either side is single-rep (min of the two)
    assert pg.noise_band("single_client_tasks_sync", 3) < pg.noise_band(
        "single_client_tasks_sync", 1)
    assert pg.noise_band("multi_client_tasks_async", 3) > pg.noise_band(
        "single_client_tasks_sync", 3)
    report = pg.compare({"a": 100.0, "gone": 50.0},
                        {"a": 300.0, "fresh": 10.0},
                        base_reps=3, cur_reps=3)
    # out-of-band rises are flagged as improvements, not silently passed
    assert report["metrics"]["a"]["status"] == "improved"
    assert "a" in report["improvements"]
    # metric coverage changes are informational, never failures
    assert report["metrics"]["fresh"]["status"] == "new"
    assert report["metrics"]["gone"]["status"] == "missing"
    assert report["status"] == "pass"


@pytest.mark.fast
def test_band_scale_env_override(monkeypatch):
    base = pg.noise_band("single_client_tasks_sync", 3)
    monkeypatch.setenv("RTPU_perf_band_scale", "2.0")
    assert pg.noise_band("single_client_tasks_sync", 3) == pytest.approx(
        2.0 * base)


# ----------------------------------------------------------------- ledger


@pytest.mark.fast
def test_ledger_append_compare_roundtrip(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    assert pg.load_history(path=path) == []
    assert pg.load_baseline(path=path) is None
    pg.append_history({"m": 100.0}, path=path, reps=3, note="r1")
    pg.append_history({"m": 104.0, "k": 7.0}, path=path, reps=3, note="r2")
    entries = pg.load_history(path=path)
    assert [e["note"] for e in entries] == ["r1", "r2"]
    base = pg.load_baseline(path=path)
    assert base["metrics"] == {"m": 104.0, "k": 7.0} and base["reps"] == 3
    report = pg.compare(entries[0]["metrics"], entries[1]["metrics"],
                        entries[0]["reps"], entries[1]["reps"])
    assert report["status"] == "pass"
    assert report["metrics"]["m"]["status"] == "pass"
    # a torn line must not brick the ledger
    with open(path, "a") as f:
        f.write('{"metrics": {"m": 99')
    assert len(pg.load_history(path=path)) == 2


@pytest.mark.fast
def test_load_result_formats(tmp_path):
    v1 = tmp_path / "v1.json"
    v1.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3,
        "metrics": {"m": {"value": 10.0, "min": 9.0, "median": 10.0,
                          "max": 11.0, "reps": 3}},
    }))
    metrics, reps = pg.load_result(str(v1))
    assert metrics == {"m": 10.0} and reps == 3
    legacy = tmp_path / "legacy.json"
    legacy.write_text('{"m": 5.5}\n')
    metrics, reps = pg.load_result(str(legacy))
    assert metrics == {"m": 5.5} and reps == 1


@pytest.mark.fast
def test_perf_compare_cli_gates_regression(tmp_path, capsys):
    from ray_tpu import scripts

    base = tmp_path / "base.json"
    head = tmp_path / "head.json"
    base.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3,
        "metrics": {"single_client_tasks_sync": {"value": 1000.0}}}))
    head.write_text('{"single_client_tasks_sync": 400.0}')  # legacy format
    out_file = tmp_path / "delta.json"
    with pytest.raises(SystemExit) as e:
        scripts.main(["perf", "compare", str(base), str(head),
                      "-o", str(out_file)])
    assert e.value.code == 1
    report = json.loads(out_file.read_text())
    assert report["status"] == "fail"
    assert "single_client_tasks_sync" in report["regressions"]
    assert "regression" in capsys.readouterr().out.lower()
    # passing pair exits cleanly
    head.write_text('{"single_client_tasks_sync": 950.0}')
    scripts.main(["perf", "compare", str(base), str(head)])


@pytest.mark.fast
def test_load_result_entry_carries_host_cpus(tmp_path):
    v1 = tmp_path / "v1.json"
    v1.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3, "host": {"cpus": 8},
        "metrics": {"m": {"value": 10.0}}}))
    entry = pg.load_result_entry(str(v1))
    assert entry["metrics"] == {"m": 10.0}
    assert entry["reps"] == 3 and entry["cpus"] == 8
    legacy = tmp_path / "legacy.json"
    legacy.write_text('{"m": 5.5}\n')
    entry = pg.load_result_entry(str(legacy))
    assert entry["cpus"] is None  # predates host.cpus: unknown, not wrong


@pytest.mark.fast
def test_perf_compare_annotates_core_count_mismatch(monkeypatch, tmp_path,
                                                    capsys):
    """A 1-core measurement compared against a multi-core one must never
    silently gate: the report is annotated, and --skip-noisy skips it.
    (is_noisy_runner is pinned False so the single-core skip path of the
    box running this test doesn't shadow the mismatch path.)"""
    from ray_tpu import scripts

    monkeypatch.setattr(pg, "is_noisy_runner", lambda: False)
    base = tmp_path / "base.json"
    head = tmp_path / "head.json"
    base.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3, "host": {"cpus": 8},
        "metrics": {"multi_client_tasks_async": {"value": 20000.0}}}))
    head.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3, "host": {"cpus": 1},
        "metrics": {"multi_client_tasks_async": {"value": 3000.0}}}))
    out_file = tmp_path / "delta.json"
    # annotated (and still gating) without --skip-noisy
    with pytest.raises(SystemExit) as e:
        scripts.main(["perf", "compare", str(base), str(head),
                      "-o", str(out_file)])
    assert e.value.code == 1
    report = json.loads(out_file.read_text())
    assert report["host_mismatch"] == {"baseline_cpus": 8, "current_cpus": 1}
    assert "cpus" in capsys.readouterr().out
    # --skip-noisy: cross-core-count comparison skipped cleanly (exit 0)
    scripts.main(["perf", "compare", str(base), str(head), "--skip-noisy",
                  "-o", str(out_file)])
    report = json.loads(out_file.read_text())
    assert report["status"] == "skipped"
    assert "core-count mismatch" in report["reason"]
    # same-core-count comparisons are untouched by the new path
    head.write_text(json.dumps({
        "schema": "microbench.v1", "reps": 3, "host": {"cpus": 8},
        "metrics": {"multi_client_tasks_async": {"value": 19000.0}}}))
    scripts.main(["perf", "compare", str(base), str(head)])


@pytest.mark.fast
def test_perf_check_advisory_on_host_mismatch(monkeypatch, tmp_path):
    """`perf check` against a ledger head recorded on a different core
    count demotes regressions to advisory (the 1-core-CI-vs-multi-core
    guard), unless --strict."""
    from ray_tpu import scripts

    hist = tmp_path / "hist.jsonl"
    entry = {"time": 1.0, "reps": 1, "host": {"cpus": 64},
             "metrics": {"single_client_tasks_sync": 1_000_000.0}}
    hist.write_text(json.dumps(entry) + "\n")
    monkeypatch.setattr(pg, "run_microbench", lambda only=None, quick=True: {
        "schema": "microbench.v1", "reps": 1,
        "host": {"cpus": os.cpu_count()},
        "metrics": {"single_client_tasks_sync": {"value": 10.0}}})
    monkeypatch.setattr(pg, "is_noisy_runner", lambda: False)
    # huge drop, but measured on a different box shape: advisory exit 0
    scripts.main(["perf", "check", "--history", str(hist)])
    # --strict restores the hard failure
    with pytest.raises(SystemExit) as e:
        scripts.main(["perf", "check", "--history", str(hist), "--strict"])
    assert e.value.code == 1


@pytest.mark.fast
def test_perf_check_advisory_on_noisy_runner(monkeypatch, tmp_path):
    """Cross-time ledger comparisons on a single-core box can't tell
    co-tenant load from a code regression: `perf check` downgrades to
    advisory (exit 0 + flagged report) there unless --strict; multi-core
    boxes and the CI A/B path stay strict."""
    from ray_tpu import scripts

    ledger = str(tmp_path / "h.jsonl")
    pg.append_history({"m": 1000.0}, path=ledger, reps=3)
    canned = {"schema": "microbench.v1", "reps": 1,
              "metrics": {"m": {"value": 100.0}}}
    monkeypatch.setattr(pg, "run_microbench", lambda **kw: canned)
    monkeypatch.setattr(pg, "is_noisy_runner", lambda: True)
    scripts.main(["perf", "check", "--history", ledger,
                  "-o", str(tmp_path / "r.json")])  # no SystemExit
    rep = json.loads((tmp_path / "r.json").read_text())
    assert rep["status"] == "fail" and rep["advisory"] is True
    with pytest.raises(SystemExit) as e:
        scripts.main(["perf", "check", "--history", ledger, "--strict"])
    assert e.value.code == 1
    monkeypatch.setattr(pg, "is_noisy_runner", lambda: False)
    with pytest.raises(SystemExit) as e:
        scripts.main(["perf", "check", "--history", ledger])
    assert e.value.code == 1


# ------------------------------------------------- compile-storm detection


def _manual_clock():
    t = {"now": 1000.0}

    def clock():
        return t["now"]

    return t, clock


def _recorder(clock):
    from ray_tpu.train._telemetry import StepRecorder

    return StepRecorder(emit_metrics=False, emit_spans=False, clock=clock,
                        wall_clock=clock, devices=[])


@pytest.mark.fast
def test_compile_storm_detection_after_warmup():
    t, clock = _manual_clock()
    rec = _recorder(clock)
    # warmup: the first compile is expected and never counted
    rec.record_step(1.0, compile_step=True)
    for _ in range(6):
        t["now"] += 0.1
        rec.record_step(0.1)
    assert rec.pop_compile_storm() is None
    # three post-warmup recompiles inside the window (default K=3, 120s)
    for _ in range(3):
        t["now"] += 1.0
        rec.record_step(0.5, compile_step=True)
    storm = rec.pop_compile_storm()
    assert storm is not None and storm["compiles"] >= 3
    assert storm["step"] == rec.steps
    assert rec.pop_compile_storm() is None  # cleared on read


@pytest.mark.fast
def test_compile_storm_respects_window():
    t, clock = _manual_clock()
    rec = _recorder(clock)
    rec.record_step(1.0, compile_step=True)
    for _ in range(6):
        t["now"] += 0.1
        rec.record_step(0.1)
    # compiles spread far wider than the 120s window never accumulate
    for _ in range(4):
        t["now"] += 200.0
        rec.record_step(0.5, compile_step=True)
    assert rec.pop_compile_storm() is None


class _StubGcs:
    def __init__(self):
        self.calls = []

    def call(self, method, payload, timeout=None):
        self.calls.append((method, payload))
        return {"ok": True}

    def get_all_node_info(self):
        return []


class _StubCore:
    mode = "driver"
    node_id = None
    is_shutdown = False
    worker_id = b"\x01" * 16
    tasks_completed = 0
    _pending_tasks = {}
    session_dir = ""

    def __init__(self):
        self.gcs = _StubGcs()


def test_watchdog_promotes_storm_to_incident(monkeypatch):
    # incident publishing must not depend on a live cluster capture
    monkeypatch.setenv("RTPU_profile_on_incident", "0")
    from ray_tpu._private.watchdog import StallWatchdog
    from ray_tpu.train import _telemetry

    t, clock = _manual_clock()
    rec = _recorder(clock)
    rec.record_step(1.0, compile_step=True)
    for _ in range(6):
        t["now"] += 0.1
        rec.record_step(0.1)
    for _ in range(3):
        t["now"] += 1.0
        rec.record_step(0.5, compile_step=True)
    prev = _telemetry.current_recorder()
    _telemetry.set_current_recorder(rec)
    try:
        core = _StubCore()
        wd = StallWatchdog(core)
        wd.check()
        incidents = [p["incident"] for m, p in core.gcs.calls
                     if m == "ReportIncident"]
        storms = [i for i in incidents if i["kind"] == "jit_cache_miss_storm"]
        assert storms, incidents
        inc = storms[0]
        assert inc["compile_storm"]["compiles"] >= 3
        assert "retraced" in inc["detail"]
        # rate-limited: an immediate second storm does not refire
        rec.record_step(0.5, compile_step=True)
        rec.record_step(0.5, compile_step=True)
        rec.record_step(0.5, compile_step=True)
        wd.check()
        incidents2 = [p["incident"] for m, p in core.gcs.calls
                      if m == "ReportIncident"
                      and p["incident"]["kind"] == "jit_cache_miss_storm"]
        assert len(incidents2) == 1
    finally:
        _telemetry.set_current_recorder(prev)


# ------------------------------------------------------ incident analysis


def _synthetic_trace():
    node = {"pid": "node:aa", "tid": "cpu:worker:1:MainThread"}
    return {"traceEvents": [
        {"cat": "cpu_sample", "ph": "X", "ts": 0.0, "dur": 600_000.0,
         "name": "compile",
         "args": {"stack": "MainThread;train;jax;pxla;backend_compile",
                  "samples": 60}, **node},
        {"cat": "cpu_sample", "ph": "X", "ts": 0.0, "dur": 400_000.0,
         "name": "read_batch",
         "args": {"stack": "MainThread;input;read_batch", "samples": 40},
         **node},
        {"cat": "span", "ph": "X", "ts": 0.0, "dur": 500_000.0,
         "name": "train_step.compile", **node},
        {"cat": "span", "ph": "X", "ts": 500_000.0, "dur": 500_000.0,
         "name": "train_step", **node},
        {"cat": "task_flow", "ph": "s", "id": "t1", "ts": 0.0, **node},
        {"cat": "task_flow", "ph": "f", "id": "t1", "ts": 250_000.0, **node},
        {"cat": "task", "ph": "X", "ts": 250_000.0, "dur": 750_000.0,
         "name": "f", **node},
    ]}


@pytest.mark.fast
def test_analyze_trace_extracts_shares():
    a = pa.analyze_trace(_synthetic_trace())
    assert a["cpu_seconds"] == pytest.approx(1.0)
    assert a["top_stacks"][0]["stack"].endswith("backend_compile")
    assert a["top_stacks"][0]["share"] == pytest.approx(0.6)
    assert a["compile_share"] == pytest.approx(0.6)
    assert a["compile_span_share"] == pytest.approx(0.5)
    assert a["sched_delay"]["count"] == 1
    assert a["sched_delay"]["max_ms"] == pytest.approx(250.0)
    assert a["sched_delay"]["share"] == pytest.approx(0.25)


@pytest.mark.fast
def test_attach_analysis_writes_summary_into_incident(tmp_path):
    path = tmp_path / "capture.json"
    path.write_text(json.dumps(_synthetic_trace()))
    inc = {"kind": "jit_cache_miss_storm", "profile_path": str(path)}
    assert pa.attach_analysis(inc)
    summary = inc["analysis"]["summary"]
    assert "compile" in summary and "scheduling delay" in summary
    assert "recompilation" in summary  # storm-specific hint
    assert inc["analysis"]["top_stacks"]
    # no capture / unreadable capture leaves the incident untouched
    assert not pa.attach_analysis({"kind": "slow_step"})
    assert not pa.attach_analysis(
        {"kind": "slow_step", "profile_path": str(tmp_path / "gone.json")})


def test_watchdog_incident_carries_analysis(monkeypatch, tmp_path):
    """The full wiring: the watchdog's publish path attaches the analysis
    derived from the incident's capture before it reaches the GCS."""
    monkeypatch.setenv("RTPU_profile_on_incident", "0")
    from ray_tpu._private.watchdog import StallWatchdog

    path = tmp_path / "capture.json"
    path.write_text(json.dumps(_synthetic_trace()))
    core = _StubCore()
    wd = StallWatchdog(core)
    incident = {"kind": "slow_step", "detail": "x", "status": "open",
                "profile_path": str(path)}
    wd._publish(incident, b"")
    sent = [p["incident"] for m, p in core.gcs.calls
            if m == "ReportIncident"][0]
    assert "analysis" in sent
    assert "compile" in sent["analysis"]["summary"]


# ------------------------------------------------------- dashboard surface


@pytest.mark.fast
def test_dashboard_perf_api_serves_ledger_and_delta(monkeypatch, tmp_path):
    path = str(tmp_path / "hist.jsonl")
    pg.append_history({"m": 100.0}, path=path, reps=3, note="r1")
    pg.append_history({"m": 40.0}, path=path, reps=3, note="r2")
    monkeypatch.setenv("RTPU_perf_history_path", path)
    from ray_tpu.dashboard.head import DashboardHead

    # no live GCS behind this address: the ledger half must still serve
    head = DashboardHead("127.0.0.1:1")
    status, out = head._perf_api({"metric": "m"})
    assert status == 200
    assert [e["note"] for e in out["history"]] == ["r1", "r2"]
    assert out["delta"]["status"] == "fail"
    assert out["delta"]["metrics"]["m"]["status"] == "regression"
    assert [p["value"] for p in out["series"]] == [100.0, 40.0]
    status, out = head._perf_api({"limit": "notanint"})
    assert status == 400


# --------------------------------------------------- bench.py CPU fallback


def test_bench_cpu_fallback_emits_tagged_line(monkeypatch, capsys):
    import bench

    monkeypatch.setattr(bench, "_probe_backend",
                        lambda: (None, "tunnel refused"))

    def fake_phase(phase, attempts=2, timeout=1800, backoff_s=45.0,
                   extra_env=None):
        if phase == "framework":
            assert extra_env and extra_env["JAX_PLATFORMS"] == "cpu"
            return {"ours": 1000.0, "raw": 1100.0}
        if phase == "micro":
            return {"single_client_tasks_sync": 123.0}
        raise AssertionError(phase)

    monkeypatch.setattr(bench, "_run_phase_retry", fake_phase)
    skeleton = {"metric": "gpt2_train_tokens_per_s_via_JaxTrainer",
                "value": None, "unit": "tokens/s", "vs_baseline": None}
    bench._main_measure(skeleton)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["plane"] == "cpu" and d["status"] == "cpu_fallback"
    assert d["tunnel_error"] == "tunnel refused"
    assert d["vs_baseline"] == pytest.approx(1000.0 / 1100.0, abs=1e-3)
    assert d["micro"]["single_client_tasks_sync"] == 123.0


def test_bench_total_outage_still_emits_line(monkeypatch, capsys):
    import bench

    monkeypatch.setattr(bench, "_probe_backend", lambda: (None, "down"))

    def fail_phase(phase, **kw):
        raise RuntimeError("cpu also broken")

    monkeypatch.setattr(bench, "_run_phase_retry", fail_phase)
    skeleton = {"metric": "gpt2_train_tokens_per_s_via_JaxTrainer",
                "value": None, "unit": "tokens/s", "vs_baseline": None}
    bench._main_measure(skeleton)
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["status"] == "tunnel_down" and d["plane"] == "none"


# ----------------------------------------------------------- tier-1 smoke


@pytest.mark.timeout(170)
def test_perf_check_only_smoke(tmp_path):
    """`ray-tpu perf check --only single_client_put_calls --quick` runs the
    REAL microbench subset in a subprocess, passes on a clean tree (no
    baseline -> every metric lands as `new`), and --update seeds the
    ledger; the second comparison path is covered by the fast unit tests
    above (a second live run would double the smoke's wall time)."""
    from ray_tpu import scripts

    ledger = str(tmp_path / "hist.jsonl")
    rc = 0
    try:
        scripts.main(["perf", "check", "--only", "single_client_put_calls",
                      "--quick", "--history", ledger, "--update",
                      "-o", str(tmp_path / "report.json")])
    except SystemExit as e:
        rc = e.code or 0
    assert rc == 0
    entries = pg.load_history(path=ledger)
    assert len(entries) == 1
    assert entries[0]["metrics"]["single_client_put_calls"] > 0
    assert entries[0]["reps"] == 1 and entries[0]["quick"]
    report = json.loads((tmp_path / "report.json").read_text())
    assert report["status"] == "pass"
    assert (report["metrics"]["single_client_put_calls"]["status"] == "new")
