"""Extended ray_tpu.data surface: file IO, sort/groupby/aggregates/zip,
preprocessors (reference: python/ray/data/tests/ — the corresponding
test_{parquet,csv,json,sort,groupby,preprocessors} files)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.preprocessors import (
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    StandardScaler,
)


@pytest.fixture(scope="module")
def data_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_parquet_roundtrip(data_cluster, tmp_path):
    ds = rd.range(100, override_num_blocks=3)
    files = ds.write_parquet(str(tmp_path / "pq"))
    assert len(files) == 3
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 100
    assert sorted(r["id"] for r in back.take_all()) == list(range(100))


def test_csv_roundtrip(data_cluster, tmp_path):
    ds = rd.from_items(
        [{"a": i, "b": float(i) * 0.5} for i in range(50)],
        override_num_blocks=2,
    )
    ds.write_csv(str(tmp_path / "csv"))
    back = rd.read_csv(str(tmp_path / "csv"))
    assert back.count() == 50
    assert back.sum("a") == sum(range(50))


def test_json_roundtrip(data_cluster, tmp_path):
    ds = rd.from_items([{"x": i} for i in range(30)], override_num_blocks=2)
    ds.write_json(str(tmp_path / "js"))
    back = rd.read_json(str(tmp_path / "js"))
    assert back.count() == 30
    assert back.max("x") == 29


def test_read_text(data_cluster, tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("hello\nworld\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]


def test_from_to_pandas(data_cluster):
    import pandas as pd

    df = pd.DataFrame({"a": [3, 1, 2], "b": ["x", "y", "z"]})
    ds = rd.from_pandas(df)
    out = ds.to_pandas()
    assert list(out["a"]) == [3, 1, 2]
    assert list(out["b"]) == ["x", "y", "z"]


def test_sort_limit_unique(data_cluster):
    ds = rd.from_items([{"v": x} for x in [5, 3, 8, 1, 9, 3]])
    s = ds.sort("v")
    assert [r["v"] for r in s.take_all()] == [1, 3, 3, 5, 8, 9]
    d = ds.sort("v", descending=True)
    assert [r["v"] for r in d.take_all()] == [9, 8, 5, 3, 3, 1]
    assert [r["v"] for r in s.limit(2).take_all()] == [1, 3]
    assert ds.unique("v") == [1, 3, 5, 8, 9]


def test_aggregates(data_cluster):
    ds = rd.range(10)
    assert ds.sum("id") == 45
    assert ds.mean("id") == pytest.approx(4.5)
    assert ds.min("id") == 0
    assert ds.max("id") == 9


def test_groupby(data_cluster):
    ds = rd.from_items(
        [{"k": i % 3, "v": i} for i in range(12)], override_num_blocks=3
    )
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    means = ds.groupby("k").mean("v").take_all()
    assert means[0]["mean(v)"] == pytest.approx(4.5)


def test_map_groups(data_cluster):
    ds = rd.from_items([{"k": i % 2, "v": i} for i in range(8)])
    out = ds.groupby("k").map_groups(
        lambda g: {"k": g["k"][:1], "n": np.asarray([len(g["v"])])}
    )
    assert sorted((r["k"], r["n"]) for r in out.take_all()) == [(0, 4), (1, 4)]


def test_zip(data_cluster):
    a = rd.range(5)
    b = rd.from_items([{"sq": i * i} for i in range(5)])
    z = a.zip(b)
    rows = z.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_standard_scaler(data_cluster):
    ds = rd.from_items([{"x": float(i)} for i in range(100)])
    sc = StandardScaler(["x"])
    out = sc.fit_transform(ds)
    vals = np.array([r["x"] for r in out.take_all()])
    assert abs(vals.mean()) < 1e-9
    assert vals.std() == pytest.approx(1.0, abs=1e-9)


def test_minmax_label_onehot(data_cluster):
    ds = rd.from_items(
        [{"x": float(i), "cat": ["a", "b", "c"][i % 3]} for i in range(9)]
    )
    mm = MinMaxScaler(["x"]).fit_transform(ds)
    vals = [r["x"] for r in mm.take_all()]
    assert min(vals) == 0.0 and max(vals) == 1.0

    le = LabelEncoder("cat").fit_transform(ds)
    codes = {r["cat"] for r in le.take_all()}
    assert codes == {0, 1, 2}

    oh = OneHotEncoder(["cat"]).fit_transform(ds)
    row = oh.take(1)[0]
    assert {"cat_a", "cat_b", "cat_c"} <= set(row)


def test_concatenator_chain(data_cluster):
    ds = rd.from_items(
        [{"a": float(i), "b": float(-i), "y": i % 2} for i in range(20)]
    )
    pipe = Chain(StandardScaler(["a", "b"]), Concatenator(["a", "b"]))
    out = pipe.fit_transform(ds)
    row = out.take(1)[0]
    assert row["features"].shape == (2,)
    assert "a" not in row and "b" not in row and "y" in row


def test_iter_jax_batches(data_cluster):
    import jax.numpy as jnp

    ds = rd.range(100, override_num_blocks=4)
    total = 0
    for b in ds.iter_jax_batches(batch_size=32):
        assert isinstance(b["id"], jnp.ndarray)
        total += len(b["id"])
    assert total == 100


def test_iter_jax_batches_sharded(data_cluster):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P("dp"))
    ds = rd.range(64, override_num_blocks=2)
    for b in ds.iter_jax_batches(batch_size=32, sharding=sh):
        assert len(b["id"].sharding.device_set) == 4


def test_iter_torch_batches(data_cluster):
    import torch

    ds = rd.from_items([{"x": float(i)} for i in range(50)])
    seen = 0
    for b in ds.iter_torch_batches(batch_size=16,
                                   dtypes={"x": torch.float32}):
        assert isinstance(b["x"], torch.Tensor)
        assert b["x"].dtype == torch.float32
        seen += len(b["x"])
    assert seen == 50


def test_streaming_split_alias(data_cluster):
    shards = rd.range(100, override_num_blocks=4).streaming_split(2)
    assert len(shards) == 2
    assert sum(s.count() for s in shards) == 100


def test_distributed_sort_exchange(data_cluster):
    """Sample-sort never materializes blocks on the driver (reference:
    exchange/sort_task_spec.py): the driver fetches only key samples;
    partition/merge run as tasks (asserted via task events)."""
    import ray_tpu
    import ray_tpu.data as _rd

    n = 20_000
    rng = np.random.default_rng(7)
    vals = rng.permutation(n)
    ds = rd.from_items(
        [{"v": int(x), "payload": float(x) * 0.5} for x in vals],
        override_num_blocks=8,
    )

    # count driver-side fetched bytes during sort planning
    fetched = {"bytes": 0}
    real_get = ray_tpu.get

    def counting_get(refs, **kw):
        out = real_get(refs, **kw)
        import sys

        items = out if isinstance(refs, list) else [out]
        for it in items:
            fetched["bytes"] += sum(
                getattr(v, "nbytes", sys.getsizeof(v))
                for v in (it.values() if isinstance(it, dict) else [it])
            )
        return out

    import ray_tpu.data._exchange as ex

    orig = ex.ray_tpu.get
    ex.ray_tpu.get = counting_get
    try:
        sorted_ds = ds.sort("v")
    finally:
        ex.ray_tpu.get = orig

    # driver saw only samples: a few KB, not the ~500KB dataset
    assert fetched["bytes"] < 50_000, fetched

    out = [r["v"] for r in sorted_ds.take_all()]
    assert out == sorted(vals.tolist())

    # descending too
    out_d = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert out_d == sorted(vals.tolist(), reverse=True)

    # the exchange ran as tasks, visible in task events
    from ray_tpu.util.state import list_tasks

    names = {t.get("name", "") for t in list_tasks(limit=5000)}
    assert any("_sample_block" in n for n in names), names
    assert any("_range_partition" in n for n in names)
    assert any("_sort_merge" in n for n in names)


def test_distributed_groupby_exchange(data_cluster):
    ds = rd.from_items(
        [{"k": i % 7, "v": float(i)} for i in range(10_000)],
        override_num_blocks=6,
    )
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {}
    for i in range(10_000):
        expect[i % 7] = expect.get(i % 7, 0.0) + float(i)
    assert sums == expect
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert all(v in (1428, 1429) for v in counts.values())
    means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    for k, s in expect.items():
        assert abs(means[k] - s / counts[k]) < 1e-6

    # map_groups through the exchange
    mg = ds.groupby("k").map_groups(
        lambda sub: {"k": sub["k"][:1], "n": np.asarray([len(sub["v"])])}
    )
    got = {r["k"]: r["n"] for r in mg.take_all()}
    assert got == counts


def test_sort_callable_tuple_key(data_cluster):
    """Callable keys returning tuples sort lexicographically through the
    distributed exchange (object-dtype key arrays)."""
    rows = [{"a": i % 3, "b": -i} for i in range(30)]
    ds = rd.from_items(rows, override_num_blocks=4)
    out = ds.sort(key=lambda r: (r["a"], r["b"])).take_all()
    expect = sorted(rows, key=lambda r: (r["a"], r["b"]))
    assert [(r["a"], r["b"]) for r in out] == [
        (r["a"], r["b"]) for r in expect
    ]


def test_parquet_filter_pushdown_and_arrow_bridge(data_cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    t = pa.table({"x": list(range(1000)), "y": [i * 2.0 for i in range(1000)]})
    path = tmp_path / "t.parquet"
    pq.write_table(t, path, row_group_size=100)

    ds = rd.read_parquet(str(path), columns=["x"], filter=[("x", ">=", 900)])
    rows = ds.take_all()
    assert len(rows) == 100
    assert all(r["x"] >= 900 for r in rows)
    assert "y" not in rows[0]

    # round trip through arrow
    tables = rd.from_arrow(t).to_arrow()
    merged = pa.concat_tables(tables)
    assert merged.num_rows == 1000
    assert merged.column("y").to_pylist()[:3] == [0.0, 2.0, 4.0]


def test_backpressure_policy_plugin(data_cluster):
    """A custom policy throttles per-operator concurrency (reference:
    backpressure_policy/ plugin chain)."""
    from ray_tpu.data.backpressure import (
        BackpressurePolicy,
        ConcurrencyCapBackpressurePolicy,
        DataContext,
    )

    ctx = DataContext.get_current()
    saved = list(ctx.backpressure_policies)

    class CapOne(BackpressurePolicy):
        def __init__(self):
            self.max_seen = 0

        def can_add_input(self, op, in_flight):
            self.max_seen = max(self.max_seen, in_flight)
            return in_flight < 1

    probe = CapOne()
    try:
        ctx.backpressure_policies = [probe]
        ds = rd.range(40, override_num_blocks=8)
        out = ds.map_batches(
            lambda b: {"id": b["id"] * 2}, max_in_flight=8
        ).take_all()
        assert len(out) == 40
        assert probe.max_seen <= 1  # never more than 1 in flight
    finally:
        ctx.backpressure_policies = saved

    # default chain caps at the operator's window
    assert isinstance(
        DataContext.get_current().backpressure_policies[0],
        ConcurrencyCapBackpressurePolicy,
    )
