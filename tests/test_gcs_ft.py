"""GCS fault tolerance: persistence log + kill -9 recovery.

Reference contract: the GCS persists its tables (Redis there, an append log
here — src/ray/gcs/store_client/redis_store_client.h) and every client rides
out a GCS restart via bounded reconnect retries
(gcs_rpc_server_reconnect_timeout_s). Tests kill -9 the GCS mid-run and
require the cluster to resume: existing actors keep serving (their direct
worker connections never touched the GCS), and new work (named lookups, new
actors, KV) succeeds once the monitor restarts it.
"""

import os
import time

import pytest


def test_gcs_log_replay_and_torn_tail(tmp_path):
    from ray_tpu._private.gcs.persistence import GcsLog

    path = str(tmp_path / "gcs.log")
    log = GcsLog(path)
    log.append("kv", ["ns", b"k1", b"v1"])
    log.append("kv", ["ns", b"k1", b"v2"])
    log.append("kv", ["ns", b"k2", None])
    log.append("job", {"job_id": b"j", "state": "RUNNING"})
    log.close()

    records = list(GcsLog(path).replay())
    assert records == [
        ("kv", ["ns", b"k1", b"v1"]),
        ("kv", ["ns", b"k1", b"v2"]),
        ("kv", ["ns", b"k2", None]),
        ("job", {"job_id": b"j", "state": "RUNNING"}),
    ]

    # A torn tail (crash mid-append) must not poison the intact prefix.
    with open(path, "ab") as f:
        f.write(b"\xff\xff\x00\x00partial")
    records = list(GcsLog(path).replay())
    assert len(records) == 4

    # Compaction folds the log into a snapshot that round-trips.
    log2 = GcsLog(path)
    log2.compact([("kv", ["ns", b"k1", b"v2"])])
    assert list(GcsLog(path).replay()) == [("kv", ["ns", b"k1", b"v2"])]


def test_gcs_server_restores_tables(tmp_path):
    """Boot a GcsServer, write state, boot a second one on the same log."""
    import asyncio

    from ray_tpu._private.gcs.server import GcsServer

    path = str(tmp_path / "gcs.log")

    async def run():
        s1 = GcsServer(persist_path=path)
        await s1.handle_KVPut({"ns": "fn", "key": b"a", "value": b"1"})
        await s1.handle_AddJob({"job_id": b"job1"})
        await s1.handle_CreatePlacementGroup(
            {"pg_id": b"pg1", "bundles": [{"CPU": 1.0}], "strategy": "PACK"}
        )
        s2 = GcsServer(persist_path=path)
        s2._restore()
        assert s2.kv.get("fn", b"a") == b"1"
        assert s2.jobs[b"job1"]["state"] == "RUNNING"
        assert s2.placement_groups[b"pg1"]["state"] == "PENDING"
        assert b"pg1" in s2.pending_pg_queue

    asyncio.run(run())


def test_gcs_kill9_cluster_resumes(shutdown_only):
    import ray_tpu
    from ray_tpu import api

    ray_tpu.init(num_cpus=4)
    node = api._local_node

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    counter = Counter.options(name="survivor").remote()
    assert ray_tpu.get(counter.incr.remote()) == 1

    @ray_tpu.remote
    def square(x):
        return x * x

    assert ray_tpu.get(square.remote(7)) == 49

    gcs_pid = node.processes["gcs_server"].pid
    node.kill_gcs()

    # Existing actor connections are direct worker->worker: they must keep
    # working even while the GCS is down/restarting.
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 2

    # Wait for the monitor to bring a new GCS process up on the same port.
    deadline = time.time() + 60
    while time.time() < deadline:
        proc = node.processes.get("gcs_server")
        if proc is not None and proc.pid != gcs_pid and proc.poll() is None:
            break
        time.sleep(0.2)
    else:
        pytest.fail("GCS was not restarted by the node monitor")

    # New control-plane work resumes: named lookup (restored from the log),
    # task submission (function table in restored KV), and new actors
    # (scheduling against re-registered nodes).
    found = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(found.incr.remote(), timeout=90) == 3
    assert ray_tpu.get(square.remote(9), timeout=90) == 81

    fresh = Counter.remote()
    assert ray_tpu.get(fresh.incr.remote(), timeout=90) == 1
