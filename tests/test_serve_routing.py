"""Routing + config-push hardening (reference: pow_2_scheduler.py:49
queue-length probes, _private/long_poll.py config push): two independent
handles spread load across replicas, and a scale-down completes with zero
failed requests."""

import threading
import time

import pytest


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def test_two_handles_spread_load(serve_cluster):
    """Two handles each only see their OWN in-flight counts; queue-length
    probes keep them from piling onto the same replica."""
    import ray_tpu
    from ray_tpu.serve._handle import CONTROLLER_NAME, DeploymentHandle

    serve = serve_cluster

    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Slowish:
        def __call__(self, x):
            time.sleep(0.05)
            return x

    serve.run(Slowish.bind(), name="spread", route_prefix="/spread")
    h1 = DeploymentHandle("spread#Slowish")
    h2 = DeploymentHandle("spread#Slowish")

    errs = []

    def hammer(h, n):
        try:
            resps = [h.remote(i) for i in range(n)]
            for r in resps:
                r.result(timeout=60)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t1 = threading.Thread(target=hammer, args=(h1, 30))
    t2 = threading.Thread(target=hammer, args=(h2, 30))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert not errs, errs

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    names = ray_tpu.get(controller.get_replica_names.remote("spread#Slowish"))
    counts = []
    for n in names:
        meta = ray_tpu.get(ray_tpu.get_actor(n).get_metadata.remote())
        counts.append(meta["handled"])
    total = sum(counts)
    assert total >= 60
    # both replicas took a real share (the old handle-local-only routing
    # could send ~everything from both handles to one replica)
    assert min(counts) >= total * 0.25, counts


def test_scale_down_zero_failures(serve_cluster):
    """Requests keep succeeding across a 3 -> 1 scale-down: the replica
    set change long-polls to handles and outgoing replicas drain instead
    of dying with requests in flight."""
    serve = serve_cluster

    @serve.deployment(num_replicas=3, max_ongoing_requests=8)
    class Svc:
        def __call__(self, x):
            time.sleep(0.02)
            return x * 2

    handle = serve.run(Svc.bind(), name="sd", route_prefix="/sd")

    stop = threading.Event()
    errs = []
    ok = [0]

    def client():
        i = 0
        while not stop.is_set():
            try:
                assert handle.remote(i).result(timeout=30) == i * 2
                ok[0] += 1
            except Exception as e:  # noqa: BLE001
                errs.append(e)
            i += 1

    threads = [threading.Thread(target=client) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(2)
    # scale down mid-traffic (same code version -> no rollout, just drain)
    serve.run(
        Svc.options(num_replicas=1).bind(), name="sd", route_prefix="/sd"
    )
    time.sleep(6)
    stop.set()
    for t in threads:
        t.join()
    assert not errs, f"{len(errs)} failed requests across scale-down: {errs[:3]}"
    assert ok[0] > 100

    # the set really shrank
    import ray_tpu
    from ray_tpu.serve._handle import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    deadline = time.time() + 30
    while True:
        names = ray_tpu.get(controller.get_replica_names.remote("sd#Svc"))
        if len(names) == 1:
            break
        assert time.time() < deadline, names
        time.sleep(0.5)


def test_rpc_ingress(serve_cluster):
    """Binary RPC ingress (gRPC analogue): python payloads both ways,
    method routing, typed app errors (reference: proxy.py:540)."""
    import numpy as np

    from ray_tpu.serve.rpc_ingress import RpcIngressClient, RpcIngressError

    serve = serve_cluster

    @serve.deployment
    class Model:
        def __call__(self, x):
            return {"doubled": np.asarray(x) * 2}

        def meta(self):
            return "model-v1"

        def boom(self):
            raise ValueError("bad input")

    serve.run(Model.bind(), name="rpcapp", route_prefix="/rpcapp")
    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    try:
        out = client.call("rpcapp", [1, 2, 3])
        assert out["doubled"].tolist() == [2, 4, 6]
        assert client.call("rpcapp", method="meta") == "model-v1"
        import pytest as _pytest

        with _pytest.raises(RpcIngressError, match="bad input"):
            client.call("rpcapp", method="boom")
        with _pytest.raises(RpcIngressError, match="no such application"):
            client.call("nope", 1)
    finally:
        client.close()


def test_rpc_ingress_streaming(serve_cluster):
    """Generator deployments stream chunk-by-chunk over the multiplexed
    binary ingress; the pull protocol backpressures a slow consumer
    (reference: proxy.py:540 gRPC streaming)."""
    from ray_tpu.serve.rpc_ingress import RpcIngressClient, RpcIngressError

    serve = serve_cluster

    @serve.deployment
    class Gen:
        def __init__(self):
            self.yielded = 0

        def stream(self, n):
            for i in range(n):
                self.yielded += 1
                yield {"i": i}

        def count(self):
            return self.yielded

        def broken(self):
            yield "first"
            raise RuntimeError("mid-stream-crash")

    serve.run(Gen.bind(), name="genapp", route_prefix="/genapp")
    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    try:
        # full consumption, order preserved
        items = list(client.call_streaming("genapp", 25, method="stream"))
        assert [r["i"] for r in items] == list(range(25))

        # slow consumer: pull granularity bounds the replica's run-ahead
        stream = client.call_streaming("genapp", 1000, method="stream",
                                       max_items_per_pull=4)
        consumed = []
        for _ in range(8):
            consumed.append(next(stream))
            time.sleep(0.05)
        yielded = client.call("genapp", method="count")
        # replica advanced only as far as the pull chain demanded (client
        # pulls of 4 + the proxy/replica internal pull batches of 16) —
        # nowhere near the 1000 a push model would have raced through
        assert yielded <= 80, yielded
        stream.close()

        # mid-stream generator exception surfaces as a typed error
        # (items in the same internal pull batch as the crash may be
        # dropped — batch-granular, like the native streaming path)
        stream = client.call_streaming("genapp", method="broken")
        with pytest.raises(RpcIngressError, match="mid-stream-crash"):
            for _ in stream:
                pass
    finally:
        client.close()
