"""Installability (reference: python/setup.py): the package builds a
wheel, installs into a clean target, and the runtime works from the
installed copy outside the checkout (plasma .so builds into the
per-version user cache)."""

import os
import subprocess
import sys

import pytest


@pytest.mark.timeout(600)
def test_wheel_install_and_smoke(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wheel_dir = tmp_path / "wheels"
    target = tmp_path / "site"
    r = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps",
         "--no-build-isolation", "-w", str(wheel_dir), repo],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    wheels = list(wheel_dir.glob("ray_tpu-*.whl"))
    assert wheels, list(wheel_dir.iterdir())
    r = subprocess.run(
        [sys.executable, "-m", "pip", "install", "--no-deps", "--no-index",
         "--target", str(target), str(wheels[0])],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert (target / "ray_tpu" / "_native" / "plasma_store.cc").exists()

    # run the smoke test from OUTSIDE the checkout with only the installed
    # copy importable
    smoke = tmp_path / "smoke.py"
    smoke.write_text(
        "import ray_tpu\n"
        "import ray_tpu.data as rd\n"
        "ray_tpu.init(num_cpus=2)\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x + 1\n"
        "assert ray_tpu.get(f.remote(41)) == 42\n"
        "assert rd.range(10).map(lambda r: {'v': r['id'] * 2}).count() == 10\n"
        "ray_tpu.shutdown()\n"
        "print('SMOKE-OK')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(target)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, str(smoke)], capture_output=True, text=True,
        timeout=240, cwd=str(tmp_path), env=env,
    )
    assert "SMOKE-OK" in r.stdout, (r.stdout[-2000:], r.stderr[-2000:])

    # console script installed
    assert (target / "bin" / "ray-tpu").exists()
