"""Unified profiling plane: cluster-wide CPU sampling + merged Perfetto
timeline + automatic slow-step capture.

Contracts under test:
  - the sampling profiler attributes a known hot loop correctly and its
    timestamped samples stay inside the capture window;
  - an idle (never-started) profiler costs nothing on the small-task hot
    path — nothing consults it, and probing it is sub-microsecond
    (tier-1 overhead bound);
  - `ray-tpu profile` on a 2-node cluster produces ONE Perfetto-loadable
    JSON containing CPU samples from BOTH nodes' workers time-aligned
    with task/span events (shared wall-clock µs axis);
  - a train step slower than profile_slow_step_factor x the trailing
    median raises a slow_step incident carrying a capture path whose file
    is a loadable merged trace;
  - merged-trace alignment: device-trace links, task flow events
    (SUBMITTED -> RUNNING), and CPU slices share the clock;
  - the device-trace window produces + registers a jax.profiler trace dir
    (forced on CPU);
  - timeline filters (job_id server-side, trace_id) and the trace_ctx
    enabled bit (fresh/stale workers record spans immediately).
"""

import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import sampling_profiler as sp


# ------------------------------------------------------------ the sampler


def _burn_loop(stop, tag="x"):
    x = 0
    while not stop.is_set():
        x += sum(i * i for i in range(100))
    return x


@pytest.mark.fast
def test_sampler_accuracy_on_hot_loop():
    stop = threading.Event()
    t = threading.Thread(target=_burn_loop, args=(stop,), name="hotloop")
    t.start()
    try:
        prof = sp.SamplingProfiler(hz=200, role="test")
        t0 = time.time()
        prof.start(0.6)
        result = prof.collect()
    finally:
        stop.set()
        t.join()
    assert result["role"] == "test" and result["pid"] == os.getpid()
    assert not prof.running
    # the hot loop dominates the hotloop thread's samples
    folded = sp.fold_samples(result)
    assert folded, "no samples at all"
    burn = sum(c for s, c in folded.items() if "_burn_loop" in s)
    hot_thread = sum(c for s, c in folded.items() if s.startswith("hotloop;"))
    assert hot_thread > 0.25 * 0.6 * 200, folded  # ≥25% of expected ticks
    assert burn >= 0.9 * hot_thread, folded
    # timestamped samples stay inside the capture window
    for dt, ti, si in result["samples"]:
        assert -0.01 <= dt <= (result["t1"] - result["t0"]) + 0.25
        assert 0 <= ti < len(result["threads"])
        assert 0 <= si < len(result["stacks"])
    assert result["t0"] >= t0 - 0.1 and result["t1"] >= result["t0"]


@pytest.mark.fast
def test_sampler_single_capture_per_process_and_truncation():
    # only one concurrent capture per process
    sp.start_profile(0.3, hz=50)
    with pytest.raises(RuntimeError):
        sp.start_profile(0.3, hz=50)
    first = sp.collect_profile()
    assert first is not None
    assert sp.collect_profile() is None  # cleared on read
    # sample cap: aggregation keeps going, the timeline list stops
    prof = sp.SamplingProfiler(hz=500, max_samples=5, include_idle=True)
    prof.start(0.3)
    r = prof.collect()
    assert len(r["samples"]) <= 5
    if r["truncated"]:
        assert len(r["samples"]) == 5


@pytest.mark.fast
def test_idle_profiler_costs_nothing_on_hot_path():
    """Tier-1 overhead bound. The plane is pull-only: no task/put/step hot
    path consults the profiler, so the idle cost is (a) no resident
    sampler thread and (b) the is_active probe itself being nanoseconds —
    bounded here so a regression that adds per-event work trips loudly."""
    assert not sp.is_active()
    assert not any(
        th.name.startswith("rtpu-sampler") for th in threading.enumerate())
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        sp.is_active()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 2e-6, (
        f"idle profiler probe costs {per_call * 1e6:.2f} µs")


# ---------------------------------------------------- merged-trace builder


@pytest.mark.fast
def test_merged_trace_alignment_and_links():
    from ray_tpu._private.timeline import merged_profile_trace

    t0 = 5000.0
    bundle = {
        "t0": t0, "duration": 1.0, "hz": 100.0, "errors": [], "gcs": None,
        "drivers": [],
        "nodes": [{
            "node_id": "ab" * 20,
            "profiles": [{
                "t0": t0, "t1": t0 + 1, "hz": 100.0, "pid": 7,
                "role": "worker", "threads": ["MainThread"],
                "stacks": ["f (m.py:1);g (m.py:9)"],
                "samples": [[0.10, 0, 0], [0.11, 0, 0], [0.12, 0, 0]],
                "truncated": False,
            }],
        }],
    }
    task_events = [
        {"task_id": "t1", "name": "work", "state": "SUBMITTED",
         "ts": t0 + 0.05, "node_id": "dr", "worker_id": "w0", "job_id": "j"},
        {"task_id": "t1", "name": "work", "state": "RUNNING",
         "ts": t0 + 0.10, "node_id": "ab" * 4, "worker_id": "w1",
         "job_id": "j"},
        {"task_id": "t1", "name": "work", "state": "FINISHED",
         "ts": t0 + 0.50, "node_id": "ab" * 4, "worker_id": "w1",
         "job_id": "j"},
    ]
    device = [{"path": "/tmp/dtrace", "steps": 3, "time": t0 + 0.2,
               "host": "h1"}]
    trace = merged_profile_trace(bundle, task_events, device)
    evs = trace["traceEvents"]
    # device trace is linked, not lost
    link = [e for e in evs if e.get("cat") == "device_trace"]
    assert len(link) == 1 and link[0]["args"]["path"] == "/tmp/dtrace"
    assert trace["metadata"]["device_traces"][0]["path"] == "/tmp/dtrace"
    # CPU slices and task X events share the wall-clock µs axis
    cpu = [e for e in evs if e.get("cat") == "cpu_sample"]
    task = [e for e in evs if e.get("cat") == "task" and e["ph"] == "X"]
    assert len(cpu) == 1 and len(task) == 1
    assert cpu[0]["ts"] == pytest.approx((t0 + 0.10) * 1e6, abs=1)
    assert task[0]["ts"] == pytest.approx((t0 + 0.10) * 1e6, abs=1)
    # consecutive same-stack samples collapsed into one slice
    assert cpu[0]["args"]["samples"] == 3
    # lanes group under the same node pid as the task events
    assert cpu[0]["pid"] == f"node:{'ab' * 4}" == task[0]["pid"]
    # flow events draw the SUBMITTED -> RUNNING causality edge
    flows = sorted((e for e in evs if e.get("cat") == "task_flow"),
                   key=lambda e: e["ts"])
    assert [f["ph"] for f in flows] == ["s", "f"]
    assert flows[0]["id"] == flows[1]["id"] == "t1"
    assert flows[0]["ts"] == pytest.approx((t0 + 0.05) * 1e6, abs=1)
    assert flows[1]["ts"] == pytest.approx((t0 + 0.10) * 1e6, abs=1)
    json.dumps(trace)  # serializes cleanly


# -------------------------------------------- cluster-wide capture (2 nodes)


def test_cluster_profile_two_nodes(tmp_path, shutdown_only):
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu import scripts

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"resources": {"CPU": 2, "n1": 1}},
    )
    cluster.add_node(resources={"CPU": 2, "n2": 1}, node_name="n2")
    try:
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote
        class Burner:
            def ping(self):
                return os.getpid()

            def spin_hard(self, s):
                t0 = time.time()
                x = 0
                while time.time() - t0 < s:
                    x += sum(i * i for i in range(200))
                return x

        burners = [
            Burner.options(resources={"n1": 1}).remote(),
            Burner.options(resources={"n2": 1}).remote(),
        ]
        ray_tpu.get([b.ping.remote() for b in burners])  # both workers up
        refs = [b.spin_hard.remote(12.0) for b in burners]
        time.sleep(0.3)

        out = tmp_path / "prof.json"
        scripts.main([
            "profile", "--address", cluster.address,
            "--duration", "1.2", "--hz", "150", "-o", str(out),
        ])
        trace = json.loads(out.read_text())
        evs = trace["traceEvents"]
        cpu = [e for e in evs if e.get("cat") == "cpu_sample"]
        # CPU samples from BOTH nodes' workers in one file
        worker_nodes = {
            e["pid"] for e in cpu
            if e["args"]["process"].startswith("worker:")
        }
        assert len(worker_nodes) == 2, worker_nodes
        assert any("spin_hard" in (e["args"].get("stack") or "")
                   for e in cpu), "burner frames missing"
        # ...time-aligned with task/span events: same wall-clock µs axis
        task_ts = [e["ts"] for e in evs if e.get("cat") == "task"]
        cpu_ts = [e["ts"] for e in cpu]
        assert task_ts, "no task events in merged trace"
        assert abs(min(cpu_ts) - max(task_ts)) < 300e6  # same clock epoch
        # the capture window itself brackets every CPU slice
        t0us = trace["metadata"]["capture_t0"] * 1e6
        dur_us = (trace["metadata"]["capture_duration_s"] + 2.0) * 1e6
        assert all(t0us - 1e6 <= t <= t0us + dur_us for t in cpu_ts)
        # --flame emits cluster-folded stacks with per-process attribution
        flame = tmp_path / "prof.folded"
        scripts.main([
            "profile", "--address", cluster.address,
            "--duration", "0.5", "--flame", "-o", str(flame),
        ])
        folded = flame.read_text()
        assert "spin_hard" in folded
        assert any(line.startswith("node:") for line in folded.splitlines())
        ray_tpu.get(refs)
    finally:
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        cluster.shutdown()


# ------------------------------------------------- automatic slow-step capture


def test_slow_step_triggers_incident_with_profile(monkeypatch, shutdown_only):
    monkeypatch.setenv("RTPU_watchdog_interval_s", "0.5")
    monkeypatch.setenv("RTPU_watchdog_task_timeout_s", "600")
    monkeypatch.setenv("RTPU_watchdog_step_timeout_s", "600")
    monkeypatch.setenv("RTPU_profile_slow_step_factor", "2")
    monkeypatch.setenv("RTPU_profile_trigger_duration_s", "0.5")
    from ray_tpu.train import _telemetry
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)
    rec = _telemetry.StepRecorder(emit_metrics=False, emit_spans=False)
    _telemetry.set_current_recorder(rec)
    try:
        for _ in range(10):
            rec.record_step(0.01, tokens=64)
        rec.record_step(0.5, tokens=64)  # 50x the trailing median
        deadline = time.time() + 40
        found = []
        while time.time() < deadline:
            found = [i for i in state.list_incidents()
                     if i["kind"] == "slow_step"]
            if found:
                break
            time.sleep(0.3)
        assert found, "slow_step incident never published"
        inc = found[0]
        assert "median" in inc["detail"]
        # the incident carries the capture path, and the capture is a
        # loadable merged trace with CPU samples
        path = inc.get("profile_path")
        assert path and os.path.isfile(path), inc
        trace = json.load(open(path))
        assert any(e.get("cat") == "cpu_sample"
                   for e in trace["traceEvents"])
        # the capture is registered: dashboard ?latest=1 lists it
        from ray_tpu import api
        from ray_tpu.dashboard import start_dashboard
        import urllib.request

        _, port = start_dashboard(api._local_node.gcs_address)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile?latest=1", timeout=30
        ) as resp:
            latest = json.loads(resp.read())
        assert any(c["path"] == path for c in latest["captures"])
    finally:
        _telemetry.set_current_recorder(None)


def test_slow_step_detection_median_and_cooldown():
    """Pure-recorder check: the outlier is judged against (and does not
    dilute) the trailing median; pop clears the flag."""
    from ray_tpu.train import _telemetry

    rec = _telemetry.StepRecorder(emit_metrics=False, emit_spans=False)
    rec._slow_factor = 3.0
    for _ in range(8):
        rec.record_step(0.010)
    assert rec.pop_slow_step() is None  # steady state: no flag
    rec.record_step(0.200)
    slow = rec.pop_slow_step()
    assert slow is not None
    assert slow["ratio"] == pytest.approx(20.0, rel=0.01)
    assert slow["median_s"] == pytest.approx(0.010, rel=0.01)
    assert rec.pop_slow_step() is None  # cleared on read
    # compile steps never count as slow steps
    rec.record_step(5.0, compile_step=True)
    assert rec.pop_slow_step() is None


# ------------------------------------------------------ device-trace window


def test_device_trace_window_forced_on_cpu(monkeypatch, tmp_path,
                                           shutdown_only):
    monkeypatch.setenv("RTPU_device_trace_force", "1")
    from ray_tpu._private import profiling
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.train import _telemetry

    ray_tpu.init(num_cpus=2)
    ctl = _telemetry.DeviceTraceController()
    assert ctl.supported()
    trace_dir = str(tmp_path / "dtrace")
    ctl.request(num_steps=2, trace_dir=trace_dir)
    import jax
    import jax.numpy as jnp

    for _ in range(3):  # window covers exactly 2 of these
        ctl.on_step_begin()
        out = jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
        ctl.on_step_end(out)
    # the jax profiler wrote an xplane dir
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found += [f for f in files if f.endswith(".xplane.pb")]
    assert found, f"no xplane files under {trace_dir}"
    # ...and it is registered with the GCS for the merged timeline
    regs = profiling.list_registered(get_global_worker().gcs, "device_trace")
    assert any(r["path"] == trace_dir for r in regs), regs


def test_device_trace_noop_without_force(shutdown_only):
    """On CPU (no force), arming is a silent no-op — the training loop
    must never pay for an unusable device trace."""
    from ray_tpu.train import _telemetry

    assert os.environ.get("RTPU_device_trace_force") != "1"
    ctl = _telemetry.DeviceTraceController()
    ctl.request(num_steps=1)
    ctl.on_step_begin()
    assert not ctl._active
    ctl.on_step_end()  # no crash, nothing started


# --------------------------------------------- timeline filters + tracing bit


def test_timeline_job_and_trace_filters(ray_start_regular):
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def tick(i):
        return i

    ray_tpu.get([tick.remote(i) for i in range(4)])
    tracing.enable()
    try:
        with tracing.span("filter-root") as root:
            pass
    finally:
        tracing.disable()
    my_job = get_global_worker().job_id.hex()
    deadline = time.time() + 20
    events = []
    while time.time() < deadline:
        events = ray_tpu.timeline(job_id=my_job)
        if (sum(1 for e in events if e.get("ph") == "X"
                and e.get("cat") == "task") >= 4
                and any(e.get("cat") == "span" for e in events)):
            break
        time.sleep(0.3)
    assert sum(1 for e in events if e.get("cat") == "task") >= 4
    # flow events connect submit to run for the finished tasks
    flows = [e for e in events if e.get("cat") == "task_flow"]
    assert {f["ph"] for f in flows} >= {"s", "f"}
    # a bogus job id filters everything server-side
    assert ray_tpu.timeline(job_id="ff" * 4) == []
    # trace_id keeps only that trace's spans
    spans = [e for e in events if e.get("cat") == "span"]
    tid = spans[0]["args"]["trace_id"]
    only = ray_tpu.timeline(trace_id=tid)
    assert only and all(e["args"]["trace_id"] == tid for e in only)


def test_trace_ctx_enabled_bit(ray_start_regular):
    """The spec-borne enabled bit defeats a stale disabled cache: spans in
    a worker that cached 'tracing off' still record once a traced spec
    arrives (previously dropped for up to the 5s KV TTL)."""
    from ray_tpu.util import tracing

    tracing.enable()
    try:
        ctx = tracing.context_for_spec()
        assert ctx is not None and ctx["enabled"] is True

        @ray_tpu.remote
        def stale_then_span():
            from ray_tpu.util import tracing as t

            # the executor restored this task's ctx and marked enabled
            # BEFORE user code ran — even with the KV unreachable a span
            # records immediately
            assert t.is_enabled()
            # the wire-only bit is stripped from the restored context
            assert "enabled" not in (t.current_context() or {})
            with t.span("immediate") as s:
                return s is not None

        assert ray_tpu.get(stale_then_span.remote())
        # stale-disabled cache + spec bit == enabled again (executor path)
        tracing._local_enabled, tracing._checked_at = False, time.time()
        assert not tracing.is_enabled()
        tracing._mark_enabled()
        assert tracing.is_enabled()
    finally:
        tracing.disable()
