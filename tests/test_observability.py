"""State API, task timeline, Prometheus metrics, user metrics.

Reference contracts: ray.util.state list_* (util/state/api.py),
`ray timeline` Chrome-trace dump (_private/state.py:944), Prometheus
endpoints fed by the stats pipeline (stats/metric_defs.cc,
_private/metrics_agent.py), user metrics (util/metrics.py:19).
"""

import json
import time
import urllib.request

import pytest


def _fetch(port: str | int) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def test_state_api_lists_cluster_entities(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state

    @ray_tpu.remote
    def work(x):
        return x + 1

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return "pong"

    h = Holder.options(name="held").remote()
    assert ray_tpu.get(h.ping.remote()) == "pong"
    assert ray_tpu.get([work.remote(i) for i in range(3)]) == [1, 2, 3]
    big_ref = ray_tpu.put(b"x" * (1024 * 1024))

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"
    assert nodes[0]["is_head_node"]

    actors = state.list_actors()
    assert any(a["name"] == "held" and a["state"] == "ALIVE" for a in actors)
    assert state.list_actors(filters=[("state", "=", "DEAD")]) == []

    jobs = state.list_jobs()
    assert len(jobs) == 1 and jobs[0]["status"] == "RUNNING"

    # Task events flush on a 1s cadence; poll for them.
    deadline = time.time() + 15
    tasks = []
    while time.time() < deadline:
        tasks = state.list_tasks()
        if sum(1 for t in tasks if t["state"] == "FINISHED") >= 3:
            break
        time.sleep(0.3)
    finished = [t for t in tasks if t["state"] == "FINISHED"]
    assert len(finished) >= 3
    assert any("work" in t["name"] for t in finished)

    summary = state.summarize_tasks()
    assert summary["total_tasks"] >= 3
    assert any("work" in name for name in summary["summary"])

    objs = state.list_objects()
    assert any(
        o["object_id"] == big_ref.object_id().hex() and o["pinned"] for o in objs
    )

    workers = state.list_workers()
    assert any(w["is_alive"] for w in workers)  # live actor/task workers


def test_timeline_chrome_trace(ray_start_regular, tmp_path):
    import ray_tpu

    @ray_tpu.remote
    def step():
        time.sleep(0.05)
        return 1

    ray_tpu.get([step.remote() for _ in range(4)])
    out = tmp_path / "trace.json"
    deadline = time.time() + 15
    events = []
    while time.time() < deadline:
        ray_tpu.timeline(str(out))
        events = json.loads(out.read_text())
        if sum(1 for e in events if e.get("ph") == "X") >= 4:
            break
        time.sleep(0.3)
    complete = [e for e in events if e.get("ph") == "X"]
    assert len(complete) >= 4
    for e in complete:
        # Chrome trace-event required fields; durations in microseconds.
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0.05 * 1e6 * 0.5


def test_prometheus_endpoints(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    ping = w.gcs.ping()
    assert ping["metrics_port"]
    gcs_text = _fetch(ping["metrics_port"])
    assert 'ray_tpu_gcs_nodes{state="ALIVE"} 1' in gcs_text
    assert "ray_tpu_gcs_uptime_seconds" in gcs_text

    node = w.gcs.get_all_node_info()[0]
    assert node["metrics_port"]
    raylet_text = _fetch(node["metrics_port"])
    assert "ray_tpu_node_resource_total" in raylet_text
    assert "ray_tpu_object_store_capacity_bytes" in raylet_text


def test_user_metrics_export(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    @ray_tpu.remote
    def instrumented():
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        c = Counter("app_requests_total", "requests", tag_keys=("route",))
        c.inc(3, tags={"route": "/infer"})
        Gauge("app_queue_depth", "queue").set(7)
        h = Histogram("app_latency_s", "latency", boundaries=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        return 1

    assert ray_tpu.get(instrumented.remote()) == 1
    port = worker_mod.global_worker.gcs.ping()["metrics_port"]
    deadline = time.time() + 20  # flushed on the 1s task-event cadence
    text = ""
    while time.time() < deadline:
        text = _fetch(port)
        if "app_requests_total" in text:
            break
        time.sleep(0.5)
    assert 'route="/infer"' in text
    assert "app_queue_depth" in text
    assert "app_latency_s_count" in text and "app_latency_s_bucket" in text


def test_metric_validation():
    from ray_tpu.util.metrics import Counter, Histogram

    with pytest.raises(ValueError):
        Counter("c").inc(-1)
    with pytest.raises(ValueError):
        Histogram("h")  # boundaries required
    with pytest.raises(ValueError):
        Counter("c2", tag_keys=("a",)).inc(1, tags={"b": "x"})
