"""serve.llm prefix caching + speculative decoding.

Layers under test: the refcounted prefix-sharing allocator (chained-hash
index, copy-on-write, cached-free LRU eviction, refcount-aware
free/truncate, the check_integrity leak sweep), the engine's prefix-hit
tail prefill and draft-verify speculative decode (both byte-equal to the
cold greedy baseline on fake AND real-model adapters), the
COW/preemption interaction, interrupted-admission accounting, and the
pull terminal-marker fast path.
"""

import asyncio
import time

import numpy as np
import pytest

from ray_tpu.serve.llm.adapters import FakeAdapter, build_adapter
from ray_tpu.serve.llm.engine import LLMEngine, LLMReplica, SamplingParams
from ray_tpu.serve.llm.kv_cache import KVCacheExhausted, PagedKVCache


def _cache(num_blocks=16, block_size=4, n_layers=1, heads=1, dim=2,
           prefix=True):
    return PagedKVCache(num_blocks=num_blocks, block_size=block_size,
                        n_layers=n_layers, n_kv_heads=heads, head_dim=dim,
                        enable_prefix_cache=prefix)


def _fill(c, sid, tokens):
    """allocate_cached + write the un-hit tail (token id t -> KV value t),
    mirroring the engine's admit path."""
    served = c.allocate_cached(sid, tokens, extra=1)
    assert served is not None
    tail = np.asarray(tokens[served:], np.float32)
    arr = np.broadcast_to(
        tail[None, :, None, None],
        (c.n_layers, len(tail), c.n_kv_heads, c.head_dim)).copy()
    c.write_prefill(sid, arr, arr)
    c.register_prefix(sid, tokens)
    return served


def _drain_outputs(eng, rids):
    eng.run_until_drained()
    out = []
    for r in rids:
        toks, done, reason = eng.pull(r)
        assert done
        out.append((toks, reason))
    return out


# ----------------------------------------------------- allocator: refcounts


def test_prefix_share_and_survivor_outlives_originator():
    c = _cache(num_blocks=16, block_size=4)
    toks = list(range(10))                  # 2 full blocks + partial
    assert _fill(c, "a", toks) == 0         # cold
    assert _fill(c, "b", toks) == 8         # hits both full blocks
    ta, tb = c.block_tables["a"], c.block_tables["b"]
    assert ta[:2] == tb[:2] and ta[2] != tb[2]
    assert c.ref_counts[ta[0]] == 2
    # the survivor's mapping outlives the originator
    c.free("a")
    assert c.ref_counts[tb[0]] == 1
    gk, _ = c.gather("b")
    np.testing.assert_array_equal(gk[0, :, 0, 0], np.asarray(toks, np.float32))
    # last reference drops -> indexed blocks park in cached-free, still hit
    c.free("b")
    assert c.num_used_blocks == 0 and c.num_cached_blocks == 2
    assert _fill(c, "d", toks) == 8         # cache survives with no owner
    c.free("d")
    c.assert_no_leaks()


def test_prefix_chain_hash_needs_whole_prefix():
    c = _cache(num_blocks=32, block_size=2)
    _fill(c, "a", [1, 2, 3, 4, 5])
    # same second chunk, different first chunk: chained hash must miss
    assert _fill(c, "b", [9, 9, 3, 4, 5]) == 0
    # true shared prefix, diverging tail: only the common chunks hit
    assert _fill(c, "d", [1, 2, 3, 4, 8, 8, 8]) == 4
    for s in ("a", "b", "d"):
        c.free(s)
    c.assert_no_leaks()


def test_cow_on_non_aligned_match_keeps_original_immutable():
    c = _cache(num_blocks=16, block_size=4)
    toks = [3, 1, 4, 1, 5, 9, 2, 6]          # exactly 2 full blocks
    _fill(c, "a", toks)
    # the cap (match <= len-1) maps block 1 shared but re-prefills its last
    # position -> the write must copy, not mutate the indexed block
    served = _fill(c, "b", toks)
    assert served == 7
    assert c.cow_copies == 1
    assert c.block_tables["a"][1] != c.block_tables["b"][1]
    ga, _ = c.gather("a")
    gb, _ = c.gather("b")
    np.testing.assert_array_equal(ga[0, :, 0, 0], gb[0, :, 0, 0])
    c.free("a"), c.free("b")
    c.assert_no_leaks()


def test_truncate_respects_refcounts():
    c = _cache(num_blocks=16, block_size=2)
    toks = [1, 2, 3, 4, 5]
    _fill(c, "a", toks)
    _fill(c, "b", toks)                      # shares the 2 full blocks
    used = c.num_used_blocks
    c.truncate("b", 3)                       # mid-way into shared block 1
    assert c.seq_lens["b"] == 3 and len(c.block_tables["b"]) == 2
    # a's mapping is untouched; only b's exclusive tail block went back
    assert c.num_used_blocks < used
    assert c.ref_counts[c.block_tables["a"][1]] == 2
    ga, _ = c.gather("a")
    np.testing.assert_array_equal(ga[0, :, 0, 0], np.asarray(toks, np.float32))
    # appending after the rollback copy-on-writes the still-shared block
    one = np.ones((1, 1, 2), np.float32)
    assert c.extend("b", 1)
    c.append("b", one, one)
    assert c.cow_copies == 1
    ga, _ = c.gather("a")                    # originator sees no mutation
    np.testing.assert_array_equal(ga[0, :, 0, 0], np.asarray(toks, np.float32))
    with pytest.raises(ValueError):
        c.truncate("b", 99)
    c.free("a"), c.free("b")
    c.assert_no_leaks()


def test_cached_free_lru_eviction_under_pressure():
    c = _cache(num_blocks=4, block_size=2)
    _fill(c, "a", [1, 2, 3])                 # 2 blocks, 1 indexed
    c.free("a")
    assert c.num_cached_blocks == 1
    # demand the whole pool: the cached block is evicted, index pruned
    assert c.allocate("big", 8)
    assert c.num_cached_blocks == 0 and c.prefix_evictions == 1
    assert _fill.__name__  # (no index entries may survive the evict)
    assert c.match_prefix([1, 2, 3]) == ([], 0)
    c.free("big")
    c.assert_no_leaks()


def test_allocate_cached_rolls_back_partial_hold_on_exhaustion():
    """Satellite: an interrupted admission must free partially-held blocks
    — with refcounts a leak here pins shared blocks forever."""
    c = _cache(num_blocks=4, block_size=2)
    _fill(c, "a", [1, 2, 3])                 # 2 blocks (1 indexed full)
    snapshot_refs = c.ref_counts.copy()
    # prefix hit on the full block, but the 5-token tail cannot fit the
    # 2 remaining blocks: the matched incref must be rolled back
    assert c.allocate_cached("b", [1, 2, 3, 4, 5, 6, 7], extra=1) is None
    np.testing.assert_array_equal(c.ref_counts, snapshot_refs)
    assert "b" not in c.block_tables
    c.assert_no_leaks()
    c.free("a")
    c.assert_no_leaks()


# ------------------------------------------------------ engine: prefix hits


def _gpt2(seed=0):
    return build_adapter(
        "gpt2-tiny",
        {"n_layer": 2, "n_embd": 64, "n_head": 4, "vocab_size": 96,
         "block_size": 128, "use_flash_attention": False}, seed=seed)


def test_engine_prefix_hit_outputs_byte_equal_gpt2():
    prompt = list(range(1, 20))
    cold = LLMEngine(_gpt2(), num_blocks=128, block_size=4, max_batch=4,
                     prefix_cache=False)
    (ref, reason), = _drain_outputs(
        cold, [cold.submit(prompt, SamplingParams(max_tokens=8))])
    assert reason == "length" and cold.cache.prefix_hit_tokens == 0

    warm = LLMEngine(_gpt2(), num_blocks=128, block_size=4, max_batch=2,
                     prefix_cache=True)
    rids = [warm.submit(prompt, SamplingParams(max_tokens=8))
            for _ in range(5)]
    outs = _drain_outputs(warm, rids)
    assert all(o == (ref, "length") for o in outs)
    assert warm.cache.prefix_hit_tokens > 0
    assert 0 < warm.cache.hit_rate() < 1
    warm.cache.assert_no_leaks()
    assert warm.cache.num_used_blocks == 0


def test_engine_prefix_cow_on_aligned_prompt_byte_equal():
    prompt = [5, 9, 17, 3, 11, 2, 7, 1]      # exactly 2 blocks of 4
    cold = LLMEngine(_gpt2(), num_blocks=64, block_size=4, max_batch=4,
                     prefix_cache=False)
    (ref, _), = _drain_outputs(
        cold, [cold.submit(prompt, SamplingParams(max_tokens=6))])
    warm = LLMEngine(_gpt2(), num_blocks=64, block_size=4, max_batch=4,
                     prefix_cache=True)
    r1 = warm.submit(prompt, SamplingParams(max_tokens=6))
    warm.step()                              # r1 prefilled + indexed, alive
    rids = [r1] + [warm.submit(prompt, SamplingParams(max_tokens=6))
                   for _ in range(2)]
    outs = _drain_outputs(warm, rids)
    assert all(o == (ref, "length") for o in outs)
    # the cap (match <= len-1) re-prefills the last position of a block r1
    # still references: the write must copy, not mutate r1's KV
    assert warm.cache.cow_copies >= 1
    warm.cache.assert_no_leaks()


def test_cow_preempt_interaction_survivor_and_recompute():
    """Satellite: preempting the youngest of two prefix-sharing sequences
    must not free blocks the survivor maps, and the recompute must re-hit
    the prefix cache and still produce byte-equal output."""
    prompt = [7, 8, 9, 10, 11, 12, 13, 14, 15]
    ref_eng = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                        block_size=2, max_batch=4, prefix_cache=False)
    (ref, _), = _drain_outputs(
        ref_eng, [ref_eng.submit(prompt, SamplingParams(max_tokens=12))])

    # pool sized to hold ONE fully-grown sequence (9 + 12 + 1 tokens = 11
    # blocks) but not two, so decoding must preempt the youngest
    tiny = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=14,
                     block_size=2, max_batch=4, prefix_cache=True)
    old = tiny.submit(prompt, SamplingParams(max_tokens=12))
    tiny.step()                              # prefill + index old's blocks
    young = tiny.submit(prompt, SamplingParams(max_tokens=12))
    tiny.step()                              # young admits via the index
    hits_before = tiny.cache.prefix_hit_tokens
    assert hits_before > 0
    while tiny.scheduler.preemptions_total == 0 and tiny.has_work():
        tiny.step()
        # the survivor's shared blocks must stay mapped and consistent
        tiny.cache.assert_no_leaks()
    assert tiny.scheduler.preemptions_total > 0
    outs = _drain_outputs(tiny, [old, young])
    assert all(o == (ref, "length") for o in outs)
    # the preempted sequence's recompute re-hit the prefix cache
    assert tiny.cache.prefix_hit_tokens > hits_before
    tiny.cache.assert_no_leaks()
    assert tiny.cache.num_used_blocks == 0


def test_interrupted_admission_requeues_without_leak():
    """Satellite: KVCacheExhausted mid-prefill frees the partial hold
    before the sequence re-queues (leak checked by the integrity sweep)."""
    eng = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=32, block_size=2,
                    max_batch=4, prefix_cache=True)
    ref_rid = eng.submit([1, 2, 3, 4, 5], SamplingParams(max_tokens=6))
    (ref, _), = _drain_outputs(eng, [ref_rid])
    eng.cache.assert_no_leaks()

    boom = {"armed": True}
    orig = eng.cache.write_prefill

    def exploding_write(seq_id, k, v):
        if boom["armed"]:
            boom["armed"] = False
            raise KVCacheExhausted("injected mid-admission failure")
        return orig(seq_id, k, v)

    eng.cache.write_prefill = exploding_write
    rid = eng.submit([1, 2, 3, 4, 5], SamplingParams(max_tokens=6))
    st = eng.step()                          # admission fails, requeues
    assert st["tokens"] == 0
    seq = eng.scheduler.get(rid)
    assert seq is not None and seq.state == "WAITING"
    eng.cache.assert_no_leaks()              # nothing pinned by the failure
    (out, reason), = _drain_outputs(eng, [rid])   # next step retries fine
    assert (out, reason) == (ref, "length")
    eng.cache.assert_no_leaks()
    assert eng.cache.num_used_blocks == 0


# -------------------------------------------------- engine: speculative


def test_spec_decode_byte_equal_partial_acceptance():
    mk_ref = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                       block_size=4, max_batch=4, prefix_cache=False)
    (ref, _), = _drain_outputs(
        mk_ref, [mk_ref.submit([7, 8, 9], SamplingParams(max_tokens=20))])

    spec = LLMEngine(
        FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
        max_batch=4,
        draft_adapter=FakeAdapter(vocab_size=97, disagree_every=7),
        spec_k=4)
    rids = [spec.submit([7, 8, 9], SamplingParams(max_tokens=20))
            for _ in range(3)]
    outs = _drain_outputs(spec, rids)
    assert all(o == (ref, "length") for o in outs)
    assert spec.spec_rounds_total > 0
    assert 0.0 < spec.spec_acceptance() < 1.0    # partial, deterministic
    # fewer target steps than tokens is the whole point
    assert spec.steps_total < 3 * 20
    spec.cache.assert_no_leaks()
    spec.draft_cache.assert_no_leaks()
    assert spec.cache.num_used_blocks == 0
    assert spec.draft_cache.num_used_blocks == 0


def test_spec_decode_zero_acceptance_still_byte_equal():
    mk_ref = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                       block_size=4, max_batch=2, prefix_cache=False)
    (ref, _), = _drain_outputs(
        mk_ref, [mk_ref.submit([3, 5], SamplingParams(max_tokens=10))])
    # disagree_every=1 perturbs EVERY draft token: worst-case draft
    spec = LLMEngine(
        FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
        max_batch=2,
        draft_adapter=FakeAdapter(vocab_size=97, disagree_every=1),
        spec_k=3)
    (out, reason), = _drain_outputs(
        spec, [spec.submit([3, 5], SamplingParams(max_tokens=10))])
    assert (out, reason) == (ref, "length")
    assert spec.spec_acceptance() == 0.0
    spec.draft_cache.assert_no_leaks()


def test_spec_decode_eos_inside_accepted_run():
    base = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
                     max_batch=2, prefix_cache=False)
    (ref, _), = _drain_outputs(
        base, [base.submit([7, 8, 9], SamplingParams(max_tokens=20))])
    eos = ref[5]                             # terminate mid-stream
    for draft_q in (0, 7):                   # perfect and partial drafts
        b2 = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                       block_size=4, max_batch=2, prefix_cache=False)
        (r2, why2), = _drain_outputs(
            b2, [b2.submit([7, 8, 9],
                           SamplingParams(max_tokens=20, eos_id=eos))])
        spec = LLMEngine(
            FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
            max_batch=2,
            draft_adapter=FakeAdapter(vocab_size=97,
                                      disagree_every=draft_q),
            spec_k=4)
        (out, why), = _drain_outputs(
            spec, [spec.submit([7, 8, 9],
                               SamplingParams(max_tokens=20, eos_id=eos))])
        assert (out, why) == (r2, why2)
        assert why == "eos" and out == ref[:6]


def test_spec_decode_gpt2_and_llama_byte_equal():
    """Correctness bar: speculative output == non-cached greedy baseline
    on the real tiny-model adapters (prefix caching on too)."""
    for mk in (_gpt2,
               lambda seed=0: build_adapter(
                   "llama-tiny", {"vocab_size": 96, "block_size": 64,
                                  "use_flash_attention": False}, seed=seed)):
        prompt = [5, 9, 17, 3]
        cold = LLMEngine(mk(), num_blocks=64, block_size=4, max_batch=4,
                         prefix_cache=False)
        (ref, _), = _drain_outputs(
            cold, [cold.submit(prompt, SamplingParams(max_tokens=8))])
        spec = LLMEngine(mk(), num_blocks=64, block_size=4, max_batch=4,
                         prefix_cache=True, draft_adapter=mk(), spec_k=3)
        rids = [spec.submit(prompt, SamplingParams(max_tokens=8))
                for _ in range(3)]
        outs = _drain_outputs(spec, rids)
        assert all(o == (ref, "length") for o in outs)
        assert spec.spec_rounds_total > 0
        spec.cache.assert_no_leaks()
        spec.draft_cache.assert_no_leaks()


def test_spec_sampled_sequences_take_plain_path():
    """Only greedy sequences speculate; a seeded-temperature sequence in
    the same batch must sample exactly as without a draft."""
    sp = dict(max_tokens=8, temperature=1.0, seed=7)
    plain = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=64,
                      block_size=4, max_batch=4)
    (ref, _), = _drain_outputs(
        plain, [plain.submit([1, 2], SamplingParams(**sp))])
    spec = LLMEngine(
        FakeAdapter(vocab_size=97), num_blocks=64, block_size=4,
        max_batch=4, draft_adapter=FakeAdapter(vocab_size=97), spec_k=4)
    r_greedy = spec.submit([1, 2], SamplingParams(max_tokens=8))
    r_temp = spec.submit([1, 2], SamplingParams(**sp))
    outs = dict(zip((r_greedy, r_temp), _drain_outputs(
        spec, [r_greedy, r_temp])))
    assert outs[r_temp] == (ref, "length")
    assert spec.spec_proposed_total > 0      # the greedy one did speculate


# ------------------------------------------------------- pull fast path


def test_pull_unknown_and_drained_return_terminal_marker():
    eng = LLMEngine(FakeAdapter(vocab_size=97), num_blocks=16, block_size=4,
                    max_batch=2)
    assert eng.pull("nope") == ([], True, "unknown")
    rid = eng.submit([1, 2], SamplingParams(max_tokens=3))
    eng.run_until_drained()
    toks, done, reason = eng.pull(rid)
    assert done and len(toks) == 3
    # drained-and-popped: terminal marker with the TRUE reason, instantly
    assert eng.pull(rid) == ([], True, "length")


def test_replica_pull_unknown_skips_long_poll():
    async def main():
        rep = LLMReplica(model="fake", model_config={"vocab_size": 97},
                         num_blocks=16, block_size=4)
        t0 = time.perf_counter()
        out = await rep.llm_pull("missing", wait_s=5.0)
        dt = time.perf_counter() - t0
        assert out["done"] and out["finish_reason"] == "unknown"
        assert dt < 1.0, f"unknown id slept the long poll: {dt:.2f}s"

    asyncio.run(main())


def test_replica_spec_and_prefix_plumbing():
    """deploy-style kwargs reach the engine: draft model, spec_k and
    prefix_cache toggles."""
    rep = LLMReplica(model="fake", model_config={"vocab_size": 97},
                     draft_model="fake",
                     draft_model_config={"vocab_size": 97,
                                         "disagree_every": 7},
                     spec_k=3, prefix_cache=True,
                     num_blocks=32, block_size=4)
    assert rep.engine.draft_cache is not None
    assert rep.engine.spec_k == 3 and rep.engine.prefix_cache_enabled
    off = LLMReplica(model="fake", model_config={"vocab_size": 97},
                     prefix_cache=False, num_blocks=32, block_size=4)
    assert off.engine.draft_cache is None
    assert not off.engine.prefix_cache_enabled
