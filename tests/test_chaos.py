"""Chaos plane scenario suite.

Three layers (reference: _private/test_utils.py RayletKiller :1536 +
nightly chaos suites):

  1. the deterministic fault-injection plane itself (`_private/chaos.py`):
     seeded plans replay the same schedule, rules match/gate correctly,
     and the rpc/plasma injection sites actually fire;
  2. serve.llm stream failover: a replica killed mid-generation surfaces
     as a transparent resubmission (prompt + tokens-so-far) to a
     surviving replica, byte-equal to a fault-free run, with exactly one
     attributed worker_crash incident per induced kill;
  3. storm-survival scenarios (@pytest.mark.slow): replica-kill storms
     under >= 32 concurrent streams, backpressure floods and slow-client
     stalls driven by the open-loop load generator — each asserting the
     end-to-end invariants (byte-equal streams, zero leaked KV blocks,
     zero leaked plasma objects, incident counts, replica-set
     reconvergence).

The seed cases (raylet SIGKILL mid-task-stream, malformed frames,
concurrent drivers, spillback) keep running unchanged at the bottom.
"""

import asyncio
import json
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu.cluster_utils import Cluster

PROMPT = [3, 1, 4, 1, 5]


def _reference_tokens(max_tokens: int, vocab: int = 97):
    """Fault-free reference: the same deterministic fake model driven by a
    local engine — the byte-equality oracle for every failover scenario."""
    from ray_tpu.serve.llm.adapters import build_adapter
    from ray_tpu.serve.llm.engine import LLMEngine, SamplingParams

    eng = LLMEngine(build_adapter("fake", {"vocab_size": vocab}),
                    num_blocks=64, block_size=4, max_batch=4)
    rid = eng.submit(PROMPT, SamplingParams(max_tokens=max_tokens))
    eng.run_until_drained()
    toks, done, reason = eng.pull(rid)
    assert done and reason == "length" and len(toks) == max_tokens
    return toks


def _poll(fn, timeout=30.0, interval=0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    return fn()


def _worker_crash_incidents():
    from ray_tpu.util import state

    return [i for i in state.list_incidents(limit=200)
            if i.get("kind") == "worker_crash"]


def _llm_integrity_all(dep: str = "llm#LLMReplica"):
    """Invariant probe on every live replica: KV refcount/free-list
    consistency + zero pinned blocks (the serve-plane leak sweep). Dead
    replicas still in the controller's set (it prunes them within one
    health window) are skipped — their blocks died with them."""
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    names = ray_tpu.get(controller.get_replica_names.remote(dep), timeout=30)
    out = {}
    for n in names:
        try:
            a = ray_tpu.get_actor(n)
            out[n] = ray_tpu.get(a.llm_call.remote("llm_integrity", (), {}),
                                 timeout=30)
        except Exception:
            continue
    return out


def _live_replicas(dep: str = "llm#LLMReplica"):
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    names = ray_tpu.get(controller.get_replica_names.remote(dep), timeout=30)
    live = []
    for n in names:
        try:
            ray_tpu.get_actor(n)
            live.append(n)
        except Exception:
            pass
    return live


# ------------------------------------------------ the fault-injection plane


def test_chaos_plan_seeded_schedule_replays_identically():
    """Same plan + same hit sequence => same injection schedule, twice
    (the acceptance bar for deterministic storms). Probabilistic rules
    draw from the rule's seeded RNG, never from global randomness."""
    plan = {"seed": 7, "rules": [
        {"site": "rpc.send", "action": "drop", "prob": 0.3, "count": 0,
         "every_n": 1},
        {"site": "replica.step", "replica": "0", "action": "kill",
         "after_steps": 5},
    ]}

    def run_schedule():
        chaos.load_plan(plan)
        fired = [bool(chaos.hit("rpc.send", method="X"))
                 for _ in range(200)]
        fired += [bool(chaos.hit("replica.step", replica="1"))
                  for _ in range(10)]  # non-matching replica: never
        fired += [bool(chaos.hit("replica.step", replica="0"))
                  for _ in range(10)]  # fires exactly once, at hit 6
        n = chaos.injections_total()
        chaos.clear()
        return fired, n

    a, na = run_schedule()
    b, nb = run_schedule()
    assert a == b and na == nb
    drops = a[:200]
    assert any(drops) and not all(drops)        # prob in (0, 1) behaved
    kills = a[210:]
    assert kills == [False] * 5 + [True] + [False] * 4


def test_chaos_rule_gating_after_every_count_and_match():
    chaos.load_plan({"rules": [
        {"site": "s", "action": "delay", "after_n": 2, "every_n": 2,
         "count": 2, "delay_s": 0.5, "tag": ["a", "b*"]},
    ]})
    try:
        seq = ["a", "bx", "c", "a", "a", "bz", "a", "a", "a"]
        fired = [bool(chaos.hit("s", tag=t)) for t in seq]
        # "c" never matches; the rest are matching hits 1..8; skip the
        # first 2 (after_n), then fire on every 2nd eligible hit
        # (eligible 2 and 4 = matching hits 4 and 6), capped at 2 fires
        assert fired == [False, False, False, False, True, False,
                         True, False, False]
        act = None
        chaos.load_plan({"rules": [
            {"site": "s", "action": "hang", "delay_s": 1.5}]})
        act = chaos.hit("s")
        assert act == {"action": "hang", "delay_s": 1.5, "rule": 0}
        assert chaos.hit("s") is None          # count defaults to 1
        assert chaos.hit("other") is None      # unknown site: no-op
    finally:
        chaos.clear()
    assert not chaos.ARMED and chaos.hit("s") is None


def test_chaos_rpc_sites_drop_dup_delay():
    """The rpc.send / rpc.recv seams against a live RpcServer: drop makes
    the caller time out (the server never sees it), recv-dup dispatches
    the handler twice for one frame, send-delay stalls the round-trip."""
    from ray_tpu._private.rpc import IoThread, RpcClient, RpcServer

    io = IoThread.current()
    calls = {"n": 0}

    async def echo(payload):
        calls["n"] += 1
        return {"v": payload["v"]}

    srv = RpcServer("127.0.0.1")
    srv.register("Echo", echo)
    port = io.run(srv.start(0))
    cli = RpcClient("127.0.0.1", port)
    io.run(cli.connect())
    try:
        chaos.load_plan({"rules": [
            {"site": "rpc.send", "method": "Echo", "action": "drop"}]})
        with pytest.raises(asyncio.TimeoutError):
            io.run(cli.call("Echo", {"v": 1}, timeout=0.5), timeout=5)
        assert calls["n"] == 0                      # never reached the wire
        assert io.run(cli.call("Echo", {"v": 2}, timeout=5),
                      timeout=10) == {"v": 2}       # rule spent: flows again
        assert chaos.injections_total() == 1

        chaos.load_plan({"rules": [
            {"site": "rpc.recv", "method": "Echo", "action": "dup"}]})
        calls["n"] = 0
        assert io.run(cli.call("Echo", {"v": 3}, timeout=5),
                      timeout=10) == {"v": 3}
        _poll(lambda: calls["n"] >= 2, timeout=5)
        assert calls["n"] == 2                      # one frame, two dispatches

        chaos.load_plan({"rules": [
            {"site": "rpc.send", "method": "Echo", "action": "delay",
             "delay_s": 0.3}]})
        t0 = time.perf_counter()
        io.run(cli.call("Echo", {"v": 4}, timeout=5), timeout=10)
        assert time.perf_counter() - t0 >= 0.28
    finally:
        chaos.clear()
        io.run(cli.close())
        io.run(srv.stop())


def test_loadgen_open_loop_schedule_and_tail():
    """The load generator is open-loop: a stalled request shows up in the
    tail (latency from the SCHEDULED arrival) without delaying later
    arrivals — coordinated omission cannot hide it."""
    from ray_tpu.util.loadgen import OpenLoopLoadGen

    a = OpenLoopLoadGen._schedule(100.0, 0.5, "poisson", 3)
    assert a == OpenLoopLoadGen._schedule(100.0, 0.5, "poisson", 3)
    assert 10 < len(a) < 200 and all(0 <= t < 0.5 for t in a)
    assert OpenLoopLoadGen._schedule(50.0, 0.2, "uniform", 0) == [
        i / 50.0 for i in range(10)]

    gate = threading.Event()

    def fn(i):
        if i == 0:
            gate.wait(5.0)
        return i

    gen = OpenLoopLoadGen(fn, rate_hz=50, duration_s=0.2, arrival="uniform")
    threading.Timer(1.0, gate.set).start()
    rep = gen.run(join_timeout_s=10)
    assert rep["completed"] == 10 and rep["failed"] == 0 and not rep["shed"]
    assert rep["max_s"] >= 0.9          # request 0's stall is in the tail
    assert rep["p50_s"] < 0.5           # nobody queued behind it


# ------------------------------------------------- failover (unit layer)


def test_llm_stream_timeout_is_structured(monkeypatch):
    """Satellite: the per-pull timeout comes from RTPU_llm_stream_timeout_s
    and surfaces as LlmStreamTimeoutError carrying stream id + tokens
    received, not a raw transport timeout."""
    import concurrent.futures

    from ray_tpu.serve import rpc_ingress as ri

    class _Io:
        def run(self, coro, timeout=None):
            coro.close()
            raise concurrent.futures.TimeoutError()

    class _Rpc:
        def call(self, *a, **k):
            async def _c():
                pass

            return _c()

    client = ri.RpcIngressClient.__new__(ri.RpcIngressClient)
    client._io = _Io()
    client._client = _Rpc()
    monkeypatch.setenv("RTPU_llm_stream_timeout_s", "7")
    s = ri.LlmStream(client, "sid-1", timeout=300.0, app="llm",
                     prompt_ids=[1, 2], sampling={"max_tokens": 8})
    s._received = [5, 6, 7]
    with pytest.raises(ri.LlmStreamTimeoutError) as ei:
        next(s)
    e = ei.value
    assert (e.stream_id == "sid-1" and e.tokens_received == 3
            and e.timeout_s == 7.0 and isinstance(e, TimeoutError))


def test_llm_stream_failover_resubmits_prompt_plus_generated(monkeypatch):
    """replica_died mid-pull => transparent reopen with prompt + tokens
    generated so far and ONLY the remaining token budget."""
    from ray_tpu.serve import rpc_ingress as ri

    monkeypatch.setenv("RTPU_serve_failover_retries", "3")
    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.01")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "0.02")

    class FakeClient:
        def __init__(self):
            self.opens = []
            self.pulls = 0
            self._io = self
            self._client = self

        def run(self, value, timeout=None):
            return value

        def call(self, method, payload, timeout=None, **kw):
            assert method == "ServeLlmNext"
            self.pulls += 1
            if self.pulls == 1:
                return {"done": False,
                        "_oob": np.asarray([11, 12], np.int32).tobytes()}
            if self.pulls == 2:
                return {"error": "actor died", "replica_died": True,
                        "app_error": True}
            return {"done": True, "finish_reason": "length",
                    "_oob": np.asarray([13], np.int32).tobytes()}

        def _llm_open(self, app, prompt, sampling, timeout):
            self.opens.append((app, list(prompt), dict(sampling)))
            return {"stream_id": f"s{len(self.opens) + 1}"}

    c = FakeClient()
    s = ri.LlmStream(c, "s1", timeout=30.0, app="llm", prompt_ids=[1, 2, 3],
                     sampling={"max_tokens": 3})
    assert list(s) == [11, 12, 13]
    assert s.failovers == 1 and s.finish_reason == "length"
    (app, prompt, sampling), = c.opens
    assert app == "llm"
    assert prompt == [1, 2, 3, 11, 12]       # prompt + generated-so-far
    assert sampling["max_tokens"] == 1       # remaining budget only


def test_llm_stream_failover_exhaustion_carries_tokens(monkeypatch):
    from ray_tpu.serve import rpc_ingress as ri

    monkeypatch.setenv("RTPU_serve_failover_retries", "2")
    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.01")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "0.02")

    class FakeClient:
        def __init__(self):
            self.pulls = 0
            self._io = self
            self._client = self

        def run(self, value, timeout=None):
            return value

        def call(self, method, payload, timeout=None, **kw):
            self.pulls += 1
            if self.pulls == 1:
                return {"done": False,
                        "_oob": np.asarray([9], np.int32).tobytes()}
            return {"error": "actor died", "replica_died": True,
                    "app_error": True}

        def _llm_open(self, app, prompt, sampling, timeout):
            raise ri.RpcIngressError("no replicas")

    s = ri.LlmStream(FakeClient(), "s1", timeout=30.0, app="llm",
                     prompt_ids=[1], sampling={"max_tokens": 4})
    assert next(s) == 9
    with pytest.raises(ri.ReplicaDiedMidStreamError) as ei:
        next(s)
    assert ei.value.tokens_generated == [9]


def test_proxy_llm_error_classifies_death_and_backpressure():
    from ray_tpu.exceptions import ActorDiedError, TaskError
    from ray_tpu.serve._proxy import ProxyActor
    from ray_tpu.serve.llm.engine import LLMBackpressure

    out = ProxyActor._llm_error(ActorDiedError(b"x", "actor died"))
    assert out["replica_died"] and out["app_error"]
    wrapped = TaskError(ActorDiedError(b"x", "dead"), "tb")
    assert ProxyActor._llm_error(wrapped)["replica_died"]
    bp = ProxyActor._llm_error(LLMBackpressure(3, 2, 0.5))
    assert bp["backpressure"] and bp["queue_depth"] == 3
    assert "replica_died" not in bp
    plain = ProxyActor._llm_error(ValueError("bad prompt"))
    assert "replica_died" not in plain


def test_proxy_llm_slot_released_exactly_once():
    """Satellite audit: every death path releases the p2c in-flight slot
    exactly once — the record pop makes a double drop a no-op."""
    from ray_tpu.serve._proxy import ProxyActor

    p = ProxyActor()
    released = []

    class H:
        def release(self, name):
            released.append(name)

    p._llm_handles = {"ing": H()}
    p._llm_streams = {"sid": {"replica": None, "name": "r0", "rid": "x",
                              "ingress": "ing", "ts": time.time()}}
    p._drop_llm_stream("sid", cancel=False)
    p._drop_llm_stream("sid", cancel=True)   # already dropped: no-op
    p._drop_llm_stream("nope", cancel=True)  # unknown: no-op
    assert released == ["r0"]


def test_handle_idempotent_retry_on_actor_died(monkeypatch):
    """DeploymentHandle bounded ActorDiedError retry: an idempotent call
    that dies with its replica re-dispatches to a survivor."""
    from ray_tpu.exceptions import ActorDiedError
    from ray_tpu.serve import _handle as H

    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.01")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "0.02")

    h = H.DeploymentHandle("dep", idempotent=True)
    assert h.options(idempotent=False)._idempotent is False
    assert h.ping._idempotent is True        # attr handles inherit it
    calls = {"redispatch": 0, "refreshed": 0}

    class GoodResp:
        def result(self, timeout=None):
            return 42

    monkeypatch.setattr(
        h, "_refresh_replicas",
        lambda force=False: calls.__setitem__(
            "refreshed", calls["refreshed"] + 1))
    monkeypatch.setattr(
        h, "_remote",
        lambda args, kwargs, died_retries=0: (
            calls.__setitem__("redispatch", calls["redispatch"] + 1),
            GoodResp())[1])

    resp = H.DeploymentResponse(object())
    resp.result = lambda timeout=None: (_ for _ in ()).throw(
        ActorDiedError(b"a", "actor died"))
    H._attach_done(resp, h, "r0", time.time(), args=(), kwargs={},
                   died_retries=2)
    assert resp.result(timeout=1) == 42
    assert calls["redispatch"] == 1 and calls["refreshed"] >= 1

    # without retries the death surfaces unchanged
    resp2 = H.DeploymentResponse(object())
    resp2.result = lambda timeout=None: (_ for _ in ()).throw(
        ActorDiedError(b"a", "actor died"))
    H._attach_done(resp2, h, "r0", time.time(), args=(), kwargs={},
                   died_retries=0)
    with pytest.raises(ActorDiedError):
        resp2.result(timeout=1)


# ------------------------------------------- failover (tier-1 fast, live)


@pytest.mark.timeout(170)
def test_llm_single_kill_failover_byte_equal(monkeypatch, shutdown_only):
    """One replica SIGKILLed mid-generation (seeded chaos plan): the
    stream transparently fails over to the controller's replacement and
    completes byte-equal to a fault-free run; exactly ONE worker_crash
    incident is published for the induced kill; the replica set
    reconverges; the surviving replica's KV is leak-free. Also exercises
    the plasma.write error site on the driver's first large put."""
    plan = {"seed": 1, "rules": [
        {"site": "replica.step", "deployment": "llm#LLMReplica", "replica": "0",
         "action": "kill", "after_steps": 6},
        {"site": "plasma.write", "action": "error", "count": 1},
    ]}
    monkeypatch.setenv("RTPU_chaos_plan", json.dumps(plan))
    monkeypatch.setenv("RTPU_serve_failover_retries", "12")
    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.5")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "2.0")
    ray_tpu.init(num_cpus=6)
    from ray_tpu import serve
    from ray_tpu.serve import llm as sllm

    try:
        # the plasma.write error rule fires on the driver's first large
        # put and ONLY that one (count=1)
        big = np.zeros(300_000, dtype=np.uint8)
        with pytest.raises(OSError, match="chaos"):
            ray_tpu.put(big)
        assert ray_tpu.get(ray_tpu.put(big)).nbytes == big.nbytes

        sllm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.05},
                    app_name="llm", num_blocks=64, block_size=4,
                    max_batch=4, max_waiting=32)
        ref = _reference_tokens(max_tokens=20)
        s = sllm.stream(PROMPT, app_name="llm", max_tokens=20)
        out = list(s)
        assert out == ref, (out, ref)
        assert s.failovers >= 1          # the kill landed mid-stream
        assert s.finish_reason == "length"

        incs = _poll(_worker_crash_incidents, timeout=30)
        assert len(incs) == 1, incs      # exactly one attributed incident
        assert incs[0].get("node_id") and incs[0].get("pid")

        # replica set reconverged to target=1 with a LIVE replica (the
        # completed stream already proves it serves)
        names = _poll(lambda: (_live_replicas()
                               if len(_live_replicas()) == 1 else None),
                      timeout=60)
        assert names and len(names) == 1

        # zero leaked KV blocks on every surviving replica
        def _clean():
            reps = _llm_integrity_all()
            return reps if all(
                not r["problems"] and r["used_blocks"] == 0
                and r["running"] == 0 for r in reps.values()) else None

        reps = _poll(_clean, timeout=30)
        assert reps, _llm_integrity_all()
    finally:
        chaos.clear()
        try:
            serve.shutdown()
        except Exception:
            pass


# --------------------------------------------- storm scenarios (slow tier)


def _run_streams(n, max_tokens, app="llm", timeout_s=240.0):
    from ray_tpu.serve import llm as sllm

    results = [None] * n
    errors = [None] * n

    def worker(i):
        try:
            results[i] = list(sllm.stream(PROMPT, app_name=app,
                                          max_tokens=max_tokens))
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.time() + timeout_s
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.time()))
    assert not any(t.is_alive() for t in threads), "streams hung"
    return results, errors


def _storm_invariants(expected_kills, ref, results, errors, target_replicas):
    """The invariant block every storm scenario asserts: byte-equal
    survivors, exact incident attribution, replica-set reconvergence,
    zero leaked KV blocks, zero leaked plasma objects."""
    from ray_tpu.util import state

    assert all(e is None for e in errors), [e for e in errors if e]
    assert all(r == ref for r in results), (
        f"{sum(r != ref for r in results)} streams diverged")

    incs = _poll(lambda: (_worker_crash_incidents()
                          if len(_worker_crash_incidents())
                          >= expected_kills else None), timeout=30)
    assert len(incs) == expected_kills, incs

    def _converged():
        names = _live_replicas()
        return names if len(names) == target_replicas else None

    assert _poll(_converged, timeout=90)

    def _clean():
        reps = _llm_integrity_all()
        return reps if reps and all(
            not r["problems"] and r["used_blocks"] == 0
            for r in reps.values()) else None

    assert _poll(_clean, timeout=30), _llm_integrity_all()

    # zero leaked plasma objects: the PR 7 forced two-sweep cross-check
    leaks = state.find_memory_leaks(sweep=True, confirm_pause_s=1.0)
    assert leaks == [], leaks


@pytest.mark.slow
@pytest.mark.timeout(280)
def test_replica_kill_storm_32_streams(monkeypatch, shutdown_only):
    """The acceptance scenario: a seeded replica-kill storm under >= 32
    concurrent llm streams. Two of three replicas are SIGKILLed at
    deterministic step counts while every stream is mid-generation; all
    32 streams must complete byte-equal to the fault-free run."""
    plan = {"seed": 5, "rules": [
        {"site": "replica.step", "deployment": "llm#LLMReplica", "replica": "0",
         "action": "kill", "after_steps": 8},
        {"site": "replica.step", "deployment": "llm#LLMReplica", "replica": "1",
         "action": "kill", "after_steps": 16},
    ]}
    monkeypatch.setenv("RTPU_chaos_plan", json.dumps(plan))
    monkeypatch.setenv("RTPU_serve_failover_retries", "20")
    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.5")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "2.0")
    ray_tpu.init(num_cpus=8)
    from ray_tpu import serve
    from ray_tpu.serve import llm as sllm

    try:
        sllm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.01},
                    app_name="llm", num_replicas=3, num_blocks=256,
                    block_size=4, max_batch=16, max_waiting=64)
        ref = _reference_tokens(max_tokens=24)
        results, errors = _run_streams(32, max_tokens=24)
        _storm_invariants(expected_kills=2, ref=ref, results=results,
                          errors=errors, target_replicas=3)
    finally:
        chaos.clear()
        try:
            serve.shutdown()
        except Exception:
            pass


@pytest.mark.slow
@pytest.mark.timeout(170)
def test_chaos_mini_storm(monkeypatch, shutdown_only):
    """CI chaos smoke: a seeded ~30s mini-storm — one replica of two
    killed under 8 concurrent streams while the raylet's heartbeat is
    chaos-delayed (the node must survive the tolerance window) — with the
    full invariant block."""
    plan = {"seed": 11, "rules": [
        {"site": "replica.step", "deployment": "llm#LLMReplica", "replica": "0",
         "action": "kill", "after_steps": 10},
        {"site": "raylet.heartbeat", "action": "drop", "count": 2},
    ]}
    monkeypatch.setenv("RTPU_chaos_plan", json.dumps(plan))
    monkeypatch.setenv("RTPU_serve_failover_retries", "15")
    monkeypatch.setenv("RTPU_serve_failover_backoff_s", "0.5")
    monkeypatch.setenv("RTPU_serve_failover_backoff_max_s", "2.0")
    ray_tpu.init(num_cpus=6)
    from ray_tpu import serve
    from ray_tpu.serve import llm as sllm
    from ray_tpu.util import state

    try:
        sllm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.02},
                    app_name="llm", num_replicas=2, num_blocks=128,
                    block_size=4, max_batch=8, max_waiting=32)
        ref = _reference_tokens(max_tokens=20)
        results, errors = _run_streams(8, max_tokens=20, timeout_s=120.0)
        _storm_invariants(expected_kills=1, ref=ref, results=results,
                          errors=errors, target_replicas=2)
        # the heartbeat drops stayed inside the failure tolerance: the
        # node is still alive in the GCS view and still schedules work
        assert state.count_open_incidents() >= 1  # the worker_crash above

        @ray_tpu.remote
        def ok():
            return 1

        assert ray_tpu.get(ok.remote(), timeout=60) == 1
    finally:
        chaos.clear()
        try:
            serve.shutdown()
        except Exception:
            pass


@pytest.mark.slow
@pytest.mark.timeout(220)
def test_backpressure_flood_sheds_cleanly(monkeypatch, shutdown_only):
    """Open-loop flood far past capacity against a tiny admission window:
    overload must shed with STRUCTURED backpressure errors (never OOM,
    never hang), completed streams stay byte-equal, and the KV pool and
    plasma store come back empty."""
    ray_tpu.init(num_cpus=6)
    from ray_tpu import serve
    from ray_tpu.serve import llm as sllm
    from ray_tpu.serve.rpc_ingress import (
        RpcBackpressureError,
        RpcIngressClient,
    )
    from ray_tpu.util.loadgen import OpenLoopLoadGen

    try:
        sllm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.05},
                    app_name="llm", num_blocks=64, block_size=4,
                    max_batch=2, max_waiting=4)
        ref = _reference_tokens(max_tokens=8)
        port = serve.start_rpc_ingress()
        client = RpcIngressClient("127.0.0.1", port)

        def fire(i):
            toks = list(client.llm_stream(PROMPT, app="llm", max_tokens=8))
            assert toks == ref
            return len(toks)

        gen = OpenLoopLoadGen(fire, rate_hz=25, duration_s=4.0,
                              arrival="poisson", seed=9)
        rep = gen.run(join_timeout_s=120)
        assert rep["completed"] >= 10
        assert rep["failed"] > 0, "flood never tripped admission control"
        # every failure is the structured shed, nothing else broke
        assert set(rep["errors"]) == {"RpcBackpressureError"}, rep["errors"]
        # the structured error itself carries the backoff numbers
        streams = []
        with pytest.raises(RpcBackpressureError) as ei:
            for _ in range(50):
                streams.append(
                    client.llm_stream(PROMPT, app="llm", max_tokens=64))
        assert ei.value.max_waiting == 4 and ei.value.queue_depth >= 4
        for s in streams:
            s.close()  # mid-stream cancels free the queued KV
        client.close()

        def _clean():
            reps = _llm_integrity_all()
            return reps if reps and all(
                not r["problems"] and r["used_blocks"] == 0
                and r["waiting"] == 0 and r["running"] == 0
                for r in reps.values()) else None

        assert _poll(_clean, timeout=90), _llm_integrity_all()
        from ray_tpu.util import state

        assert state.find_memory_leaks(sweep=True) == []
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass


@pytest.mark.slow
@pytest.mark.timeout(170)
def test_slow_client_stall_does_not_block_others(shutdown_only):
    """A client that pulls one token every 300 ms must not head-of-line
    block the batch: fast streams admitted alongside it finish promptly
    and byte-equal, the slow stream still completes, and abandoning a
    stream mid-generation frees its KV."""
    ray_tpu.init(num_cpus=6)
    from ray_tpu import serve
    from ray_tpu.serve import llm as sllm

    try:
        sllm.deploy(model="fake",
                    model_config={"vocab_size": 97, "step_cost_s": 0.01},
                    app_name="llm", num_blocks=128, block_size=4,
                    max_batch=8, max_waiting=32)
        ref = _reference_tokens(max_tokens=16)
        slow = sllm.stream(PROMPT, app_name="llm", max_tokens=16,
                           max_tokens_per_pull=1)
        slow_out = [next(slow)]
        t0 = time.time()
        results, errors = _run_streams(6, max_tokens=16, timeout_s=60.0)
        fast_elapsed = time.time() - t0
        assert all(e is None for e in errors), errors
        assert all(r == ref for r in results)
        assert fast_elapsed < 30.0, (
            f"fast streams waited {fast_elapsed:.1f}s behind a slow client")
        for t in slow:
            slow_out.append(t)
            time.sleep(0.05)
        assert slow_out == ref

        # abandonment: a stream closed mid-generation frees its blocks
        drop = sllm.stream(PROMPT, app_name="llm", max_tokens=4096)
        next(drop)
        drop.close()

        def _clean():
            reps = _llm_integrity_all()
            return reps if reps and all(
                not r["problems"] and r["used_blocks"] == 0
                for r in reps.values()) else None

        assert _poll(_clean, timeout=60), _llm_integrity_all()
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass


# ----------------------------------------------------------- seed cases


def test_raylet_killed_mid_task_stream():
    """Tasks in flight on a dying node retry elsewhere; the stream of
    submissions keeps completing (owner-side retries,
    reference: task_manager.h max_retries)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 3}}
    )
    victim = cluster.add_node(resources={"CPU": 3})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(max_retries=4)
        def work(i):
            time.sleep(0.05)
            return i * 3

        # a continuous stream: submit in waves, kill the raylet mid-wave
        refs = [work.remote(i) for i in range(60)]
        time.sleep(0.5)  # some running on the victim now
        victim.kill_raylet()
        refs += [work.remote(i) for i in range(60, 90)]
        out = ray_tpu.get(refs, timeout=180)
        assert out == [i * 3 for i in range(90)]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_malformed_rpc_frames_do_not_kill_servers():
    """Garbage bytes, huge length prefixes, and truncated frames against
    the raylet + GCS sockets: the servers drop the bad connection and keep
    serving legit traffic (reference: the gRPC layer's framing guarantees;
    our msgpack framing must be as defensive)."""
    ray_tpu.init(num_cpus=2)
    try:
        from ray_tpu import api

        node = api._local_node
        gcs_host, gcs_port = node.gcs_address.rsplit(":", 1)
        targets = [(gcs_host, int(gcs_port))]
        raylet_port = getattr(node, "raylet_port", None)
        if raylet_port:
            targets.append((gcs_host, int(raylet_port)))

        payloads = [
            b"\x00" * 64,                                 # zero-length spam
            b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",         # wrong protocol
            struct.pack("<I", (1 << 31) - 1) + b"x" * 64,  # huge frame claim
            struct.pack("<I", 100) + b"y" * 10,           # truncated body
            struct.pack("<I", 8) + b"\xc1" * 8,           # invalid msgpack
        ]
        for host, port in targets:
            for p in payloads:
                s = socket.create_connection((host, port), timeout=5)
                try:
                    s.sendall(p)
                    time.sleep(0.05)
                finally:
                    s.close()

        # the cluster still works
        @ray_tpu.remote
        def ok():
            return "alive"

        assert ray_tpu.get(ok.remote(), timeout=60) == "alive"
        assert ray_tpu.get(ok.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()


def test_two_concurrent_drivers():
    """Two independent driver processes against one cluster: both run
    tasks and actors simultaneously, with correct results and no
    cross-talk (reference: multi-driver job isolation)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 6}}
    )
    cluster.wait_for_nodes()

    script = """
import sys
import ray_tpu
tag = sys.argv[1]
ray_tpu.init(address=sys.argv[2])

@ray_tpu.remote
def f(i):
    return f"{tag}-{i}"

@ray_tpu.remote
class Acc:
    def __init__(self):
        self.items = []
    def add(self, x):
        self.items.append(x)
        return len(self.items)

a = Acc.remote()
outs = ray_tpu.get([f.remote(i) for i in range(40)])
assert outs == [f"{tag}-{i}" for i in range(40)], outs
ns = ray_tpu.get([a.add.remote(i) for i in range(20)])
assert ns == list(range(1, 21))
ray_tpu.shutdown()
print(f"DRIVER-{tag}-OK")
"""
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, tag, cluster.address],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for tag in ("one", "two")
        ]
        for tag, p in zip(("one", "two"), procs):
            out, _ = p.communicate(timeout=180)
            assert f"DRIVER-{tag}-OK" in out, out[-3000:]
    finally:
        cluster.shutdown()


def test_spillback_under_contention():
    """When the preferred node is saturated, lease requests spill to
    peers instead of queueing behind long tasks (reference:
    hybrid_scheduling_policy.cc spillback; VERDICT r2 weak #7)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(num_cpus=1)
        def hog():
            time.sleep(8)
            return ray_tpu.get_runtime_context().get_node_id()

        @ray_tpu.remote(num_cpus=1)
        def quick(i):
            return (i, ray_tpu.get_runtime_context().get_node_id())

        # saturate two slots (they land somewhere), then submit quick
        # tasks: they must run on the remaining free slots promptly, not
        # wait 8s behind the hogs
        hogs = [hog.remote() for _ in range(2)]
        time.sleep(1.0)
        t0 = time.time()
        out = ray_tpu.get([quick.remote(i) for i in range(8)], timeout=60)
        quick_elapsed = time.time() - t0
        assert quick_elapsed < 6.0, (
            f"quick tasks waited {quick_elapsed:.1f}s — no spillback past "
            "the saturated node"
        )
        assert [i for i, _ in out] == list(range(8))
        # both nodes participated overall
        hog_nodes = set(ray_tpu.get(hogs, timeout=60))
        quick_nodes = {n for _, n in out}
        assert len(hog_nodes | quick_nodes) == 2
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
