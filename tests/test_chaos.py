"""Chaos tests (reference: _private/test_utils.py RayletKiller :1536 +
nightly chaos suites): a raylet dies MID-TASK-STREAM and the stream still
completes; malformed RPC frames don't take servers down; two drivers share
one cluster concurrently."""

import socket
import struct
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def test_raylet_killed_mid_task_stream():
    """Tasks in flight on a dying node retry elsewhere; the stream of
    submissions keeps completing (owner-side retries,
    reference: task_manager.h max_retries)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 3}}
    )
    victim = cluster.add_node(resources={"CPU": 3})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(max_retries=4)
        def work(i):
            time.sleep(0.05)
            return i * 3

        # a continuous stream: submit in waves, kill the raylet mid-wave
        refs = [work.remote(i) for i in range(60)]
        time.sleep(0.5)  # some running on the victim now
        victim.kill_raylet()
        refs += [work.remote(i) for i in range(60, 90)]
        out = ray_tpu.get(refs, timeout=180)
        assert out == [i * 3 for i in range(90)]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_malformed_rpc_frames_do_not_kill_servers():
    """Garbage bytes, huge length prefixes, and truncated frames against
    the raylet + GCS sockets: the servers drop the bad connection and keep
    serving legit traffic (reference: the gRPC layer's framing guarantees;
    our msgpack framing must be as defensive)."""
    ray_tpu.init(num_cpus=2)
    try:
        from ray_tpu import api

        node = api._local_node
        gcs_host, gcs_port = node.gcs_address.rsplit(":", 1)
        targets = [(gcs_host, int(gcs_port))]
        raylet_port = getattr(node, "raylet_port", None)
        if raylet_port:
            targets.append((gcs_host, int(raylet_port)))

        payloads = [
            b"\x00" * 64,                                 # zero-length spam
            b"GET / HTTP/1.1\r\nHost: x\r\n\r\n",         # wrong protocol
            struct.pack("<I", (1 << 31) - 1) + b"x" * 64,  # huge frame claim
            struct.pack("<I", 100) + b"y" * 10,           # truncated body
            struct.pack("<I", 8) + b"\xc1" * 8,           # invalid msgpack
        ]
        for host, port in targets:
            for p in payloads:
                s = socket.create_connection((host, port), timeout=5)
                try:
                    s.sendall(p)
                    time.sleep(0.05)
                finally:
                    s.close()

        # the cluster still works
        @ray_tpu.remote
        def ok():
            return "alive"

        assert ray_tpu.get(ok.remote(), timeout=60) == "alive"
        assert ray_tpu.get(ok.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()


def test_two_concurrent_drivers():
    """Two independent driver processes against one cluster: both run
    tasks and actors simultaneously, with correct results and no
    cross-talk (reference: multi-driver job isolation)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 6}}
    )
    cluster.wait_for_nodes()

    script = """
import sys
import ray_tpu
tag = sys.argv[1]
ray_tpu.init(address=sys.argv[2])

@ray_tpu.remote
def f(i):
    return f"{tag}-{i}"

@ray_tpu.remote
class Acc:
    def __init__(self):
        self.items = []
    def add(self, x):
        self.items.append(x)
        return len(self.items)

a = Acc.remote()
outs = ray_tpu.get([f.remote(i) for i in range(40)])
assert outs == [f"{tag}-{i}" for i in range(40)], outs
ns = ray_tpu.get([a.add.remote(i) for i in range(20)])
assert ns == list(range(1, 21))
ray_tpu.shutdown()
print(f"DRIVER-{tag}-OK")
"""
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, tag, cluster.address],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for tag in ("one", "two")
        ]
        for tag, p in zip(("one", "two"), procs):
            out, _ = p.communicate(timeout=180)
            assert f"DRIVER-{tag}-OK" in out, out[-3000:]
    finally:
        cluster.shutdown()


def test_spillback_under_contention():
    """When the preferred node is saturated, lease requests spill to
    peers instead of queueing behind long tasks (reference:
    hybrid_scheduling_policy.cc spillback; VERDICT r2 weak #7)."""
    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(num_cpus=1)
        def hog():
            time.sleep(8)
            return ray_tpu.get_runtime_context().get_node_id()

        @ray_tpu.remote(num_cpus=1)
        def quick(i):
            return (i, ray_tpu.get_runtime_context().get_node_id())

        # saturate two slots (they land somewhere), then submit quick
        # tasks: they must run on the remaining free slots promptly, not
        # wait 8s behind the hogs
        hogs = [hog.remote() for _ in range(2)]
        time.sleep(1.0)
        t0 = time.time()
        out = ray_tpu.get([quick.remote(i) for i in range(8)], timeout=60)
        quick_elapsed = time.time() - t0
        assert quick_elapsed < 6.0, (
            f"quick tasks waited {quick_elapsed:.1f}s — no spillback past "
            "the saturated node"
        )
        assert [i for i, _ in out] == list(range(8))
        # both nodes participated overall
        hog_nodes = set(ray_tpu.get(hogs, timeout=60))
        quick_nodes = {n for _, n in out}
        assert len(hog_nodes | quick_nodes) == 2
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
