"""Multi-node tests over cluster_utils.Cluster
(modeled on reference python/ray/tests/test_multi_node.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


@ray_tpu.remote
def node_of():
    return ray_tpu.get_runtime_context().get_node_id()


@pytest.fixture(scope="module")
def three_node_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 2, "special": 1})
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    time.sleep(1.0)
    yield cluster
    cluster.shutdown()


def test_cluster_visible(three_node_cluster):
    assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 3
    assert ray_tpu.cluster_resources()["CPU"] == 6.0


def test_custom_resource_routing(three_node_cluster):
    @ray_tpu.remote(resources={"special": 1})
    def special():
        return ray_tpu.get_runtime_context().get_node_id()

    nid = ray_tpu.get(special.remote())
    info = next(n for n in ray_tpu.nodes() if n["NodeID"] == nid)
    assert info["Resources"].get("special") == 1.0


def test_tasks_spread_across_nodes(three_node_cluster):
    @ray_tpu.remote
    def spot(t):
        time.sleep(t)
        return ray_tpu.get_runtime_context().get_node_id()

    t0 = time.time()
    nodes_used = ray_tpu.get([spot.remote(2) for _ in range(6)])
    assert len(set(nodes_used)) >= 2
    assert time.time() - t0 < 8


def test_cross_node_object_transfer(three_node_cluster):
    @ray_tpu.remote(resources={"special": 0.5})
    def produce():
        return np.ones((1200, 1200), dtype=np.float32)

    @ray_tpu.remote
    def consume(a):
        return float(a.sum())

    ref = produce.remote()
    assert ray_tpu.get(consume.remote(ref)) == 1200 * 1200
    # driver-side pull of the same remote object
    assert ray_tpu.get(ref).shape == (1200, 1200)


def test_node_affinity(three_node_cluster):
    target = [n for n in ray_tpu.nodes() if not n["IsHead"]][0]["NodeID"]
    nid = ray_tpu.get(
        node_of.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(target)
        ).remote()
    )
    assert nid == target


def test_strict_spread_pg(three_node_cluster):
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(30)
    nodes = ray_tpu.get(
        [
            node_of.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(pg, i)
            ).remote()
            for i in range(3)
        ]
    )
    assert len(set(nodes)) == 3
    remove_placement_group(pg)


def test_strict_pack_pg(three_node_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.wait(30)
    nodes = ray_tpu.get(
        [
            node_of.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(pg, i)
            ).remote()
            for i in range(2)
        ]
    )
    assert len(set(nodes)) == 1
    remove_placement_group(pg)


def test_pg_infeasible_stays_pending(three_node_cluster):
    pg = placement_group([{"CPU": 100}], strategy="PACK")
    assert not pg.wait(1.5)


def test_actor_on_remote_node(three_node_cluster):
    @ray_tpu.remote(resources={"special": 1})
    class Pinned:
        def where(self):
            return ray_tpu.get_runtime_context().get_node_id()

    p = Pinned.remote()
    nid = ray_tpu.get(p.where.remote())
    info = next(n for n in ray_tpu.nodes() if n["NodeID"] == nid)
    assert info["Resources"].get("special") == 1.0


def test_node_death_detected(three_node_cluster):
    cluster = three_node_cluster
    victim = cluster.nodes[-1]
    victim_id = victim.node_id.hex()
    victim.kill_raylet()
    deadline = time.time() + 30
    while time.time() < deadline:
        info = {n["NodeID"]: n["Alive"] for n in ray_tpu.nodes()}
        if info.get(victim_id) is False:
            break
        time.sleep(0.5)
    else:
        pytest.fail("node death not detected")
