"""runtime_env pip venvs + py_modules (reference:
_private/runtime_env/agent/runtime_env_agent.py:162, pip.py, py_modules
via packaging.py). Offline-friendly: the test package installs from a
local source tree with --no-index."""

import os
import textwrap
import time

import pytest

import ray_tpu


@pytest.fixture
def cluster(tmp_path):
    ray_tpu.init(num_cpus=4)
    yield tmp_path
    ray_tpu.shutdown()


def _make_pkg(tmp_path, name="rtpu_test_pkg", value=41):
    pkg = tmp_path / f"{name}_src"
    (pkg / name).mkdir(parents=True)
    (pkg / name / "__init__.py").write_text(f"MAGIC = {value}\n")
    (pkg / "setup.py").write_text(textwrap.dedent(f"""
        from setuptools import setup, find_packages
        setup(name="{name}", version="1.0", packages=find_packages())
    """))
    return str(pkg)


PIP_OPTS = ["--no-index", "--no-build-isolation", "--no-deps"]


def test_pip_env_installs_and_caches(cluster):
    pkg_dir = _make_pkg(cluster)

    # the package must NOT be importable in the base env
    with pytest.raises(ImportError):
        import rtpu_test_pkg  # noqa: F401

    env = {"pip": {"packages": [pkg_dir], "pip_install_options": PIP_OPTS}}

    @ray_tpu.remote(runtime_env=env)
    def use_pkg():
        import rtpu_test_pkg

        return rtpu_test_pkg.MAGIC, rtpu_test_pkg.__file__

    magic, path = ray_tpu.get(use_pkg.remote(), timeout=180)
    assert magic == 41
    assert "runtime_envs" in path and "venvs" in path

    # cached: a second task (possibly a different worker) reuses the venv
    t0 = time.time()
    magic2, path2 = ray_tpu.get(use_pkg.remote(), timeout=60)
    assert magic2 == 41 and os.path.dirname(path2) == os.path.dirname(path)
    assert time.time() - t0 < 30  # no rebuild

    # concurrent tasks with the same env share one venv build
    outs = ray_tpu.get([use_pkg.remote() for _ in range(3)], timeout=120)
    assert all(m == 41 for m, _ in outs)


def test_pip_env_evicted_on_job_end(cluster):
    pkg_dir = _make_pkg(cluster, name="rtpu_evict_pkg", value=7)
    env = {"pip": {"packages": [pkg_dir], "pip_install_options": PIP_OPTS}}

    @ray_tpu.remote(runtime_env=env)
    def use_pkg():
        import rtpu_evict_pkg

        return os.path.dirname(os.path.dirname(rtpu_evict_pkg.__file__))

    pkg_parent = ray_tpu.get(use_pkg.remote(), timeout=180)
    venv_dir = pkg_parent  # --target dir IS the env dir
    assert os.path.isdir(venv_dir), venv_dir

    # the driver's job finishing evicts the venv (exercise the raylet's
    # JobFinished path directly — in production the GCS sends it when the
    # driver disconnects)
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    worker.io.run(
        worker.raylet.call(
            "JobFinished", {"job_id": worker.job_id.binary()}
        )
    )
    deadline = time.time() + 20
    while os.path.isdir(venv_dir):
        assert time.time() < deadline, f"venv not evicted: {venv_dir}"
        time.sleep(0.5)


def test_py_modules(cluster):
    mod_dir = cluster / "mods"
    (mod_dir / "rtpu_extra_mod").mkdir(parents=True)
    (mod_dir / "rtpu_extra_mod" / "__init__.py").write_text("WHO = 'extra'\n")

    # reference contract: each entry IS a module/package directory
    env = {"py_modules": [str(mod_dir / "rtpu_extra_mod")]}

    @ray_tpu.remote(runtime_env=env)
    def use_mod():
        import rtpu_extra_mod

        return rtpu_extra_mod.WHO

    assert ray_tpu.get(use_mod.remote(), timeout=120) == "extra"
