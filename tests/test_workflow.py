"""Durable-workflow tests (reference: python/ray/workflow/tests/ —
test_basic_workflows / checkpoint+resume semantics)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag.node import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def wf_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def wf_storage(tmp_path):
    workflow.init(storage=str(tmp_path))
    yield str(tmp_path)


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def mul(a, b):
    return a * b


def test_basic_dag(wf_cluster, wf_storage):
    dag = add.bind(1, mul.bind(2, 3))
    assert workflow.run(dag, workflow_id="w1") == 7
    assert workflow.get_status("w1") == workflow.SUCCESSFUL
    assert workflow.get_output("w1") == 7


def test_input_node(wf_cluster, wf_storage):
    with InputNode() as inp:
        dag = add.bind(inp, 10)
    assert workflow.run(dag, workflow_id="w2", input_value=5) == 15


def test_multi_output(wf_cluster, wf_storage):
    dag = MultiOutputNode([add.bind(1, 1), mul.bind(3, 3)])
    assert workflow.run(dag, workflow_id="w3") == [2, 9]


def test_checkpoints_skip_on_resume(wf_cluster, wf_storage, tmp_path):
    marker = tmp_path / "count.txt"

    @ray_tpu.remote
    def effect(x):
        with open(marker, "a") as f:
            f.write("x")
        return x * 2

    dag = add.bind(effect.bind(5), 1)
    assert workflow.run(dag, workflow_id="w4") == 11
    assert marker.read_text() == "x"
    # resume: the effect step is checkpointed, so it must NOT run again
    assert workflow.resume("w4") == 11
    assert marker.read_text() == "x"


def test_resume_after_failure(wf_cluster, wf_storage, tmp_path):
    flag = tmp_path / "fail.flag"
    flag.write_text("1")

    @ray_tpu.remote
    def stage1():
        return 41

    @ray_tpu.remote
    def flaky(x):
        if os.path.exists(flag):
            raise RuntimeError("injected failure")
        return x + 1

    dag = flaky.bind(stage1.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="w5")
    assert workflow.get_status("w5") == workflow.FAILED
    flag.unlink()
    # stage1's checkpoint survives; only flaky reruns
    assert workflow.resume("w5") == 42
    assert workflow.get_status("w5") == workflow.SUCCESSFUL


def test_continuation(wf_cluster, wf_storage):
    @ray_tpu.remote
    def fib(n):
        if n <= 1:
            return n
        return add.bind(fib.bind(n - 1), fib.bind(n - 2))

    assert workflow.run(fib.bind(7), workflow_id="w6") == 13


def test_list_and_delete(wf_cluster, wf_storage):
    workflow.run(add.bind(1, 2), workflow_id="wa")
    workflow.run(add.bind(3, 4), workflow_id="wb")
    ids = {w["workflow_id"] for w in workflow.list_all()}
    assert {"wa", "wb"} <= ids
    ok = {w["workflow_id"]
          for w in workflow.list_all(status_filter=workflow.SUCCESSFUL)}
    assert {"wa", "wb"} <= ok
    workflow.delete("wa")
    ids = {w["workflow_id"] for w in workflow.list_all()}
    assert "wa" not in ids


def test_virtual_actor_durable_state(wf_cluster, wf_storage):
    """Virtual actors: state commits per call and survives 'cluster
    loss' — resurrection from storage alone (reference: workflow virtual
    actors)."""
    from ray_tpu import workflow

    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.count = start
            self.log = []

        def add(self, n):
            self.count += n
            self.log.append(n)
            return self.count

        @workflow.virtual_actor.readonly
        def peek(self):
            return self.count

    c = Counter.get_or_create("vc-1", 10)
    assert c.add.run(5) == 15
    assert c.add.run(2) == 17
    assert c.peek.run() == 17

    # readonly did not commit a new snapshot
    # resurrect from storage in a fresh handle (as a new driver would)
    c2 = workflow.get_actor("vc-1")
    assert c2.peek.run() == 17
    assert c2.add.run(3) == 20

    # get_or_create on an existing id resumes, never resets
    c3 = Counter.get_or_create("vc-1", 999)
    assert c3.peek.run() == 20

    actors = workflow.list_actors()
    assert any(a["actor_id"] == "vc-1" for a in actors)


def test_virtual_actor_write_ordering(wf_cluster, wf_storage):
    from ray_tpu import workflow

    @workflow.virtual_actor
    class Appender:
        def __init__(self):
            self.items = []

        def push(self, x):
            self.items.append(x)
            return list(self.items)

    a = Appender.get_or_create("vc-order")
    import ray_tpu as rt

    refs = [a.push.run_async(i) for i in range(8)]
    outs = rt.get(refs, timeout=120)
    assert outs[-1] == list(range(8))
