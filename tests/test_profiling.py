"""On-demand profiling (reference: dashboard/modules/reporter/
profile_manager.py py-spy integration): sample a busy worker's stacks
through the dashboard HTTP API, flamegraph-folded output."""

import json
import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_profile_busy_worker_via_dashboard(cluster):
    from ray_tpu import api
    from ray_tpu.dashboard import start_dashboard

    _, port = start_dashboard(api._local_node.gcs_address)

    @ray_tpu.remote
    class Burner:
        def __init__(self):
            self.stop = False

        def spin_hard_loop(self, seconds):
            t0 = time.time()
            x = 0
            while time.time() - t0 < seconds:
                x += sum(i * i for i in range(200))
            return x

        def pid(self):
            import os

            return os.getpid()

    b = Burner.remote()
    pid = ray_tpu.get(b.pid.remote())
    busy_ref = b.spin_hard_loop.remote(8.0)

    time.sleep(0.5)  # let the burn start
    url = f"http://127.0.0.1:{port}/api/profile?pid={pid}&duration=2&hz=200"
    with urllib.request.urlopen(url, timeout=60) as resp:
        out = json.loads(resp.read())
    assert out.get("samples", 0) > 50, out
    assert out["pid"] == pid
    folded = out["folded"]
    # flamegraph-compatible: "thread;frame;frame N" lines, and the busy
    # method dominates
    assert "spin_hard_loop" in folded, folded[:2000]
    top = folded.splitlines()[0]
    assert top.rsplit(" ", 1)[1].isdigit()
    ray_tpu.get(busy_ref)

    # unknown pid -> 404
    bad = f"http://127.0.0.1:{port}/api/profile?pid=999999&duration=0.2"
    try:
        urllib.request.urlopen(bad, timeout=30)
        raise AssertionError("expected HTTP error")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_node_stats_in_metrics(cluster):
    """Per-node psutil stats ride the raylet's Prometheus endpoint
    (reference: reporter_agent.py:314)."""
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    nodes = worker.gcs.get_all_node_info()
    mport = nodes[0].get("metrics_port")
    assert mport, nodes[0]
    with urllib.request.urlopen(
        f"http://127.0.0.1:{mport}/metrics", timeout=30
    ) as resp:
        text = resp.read().decode()
    assert "ray_tpu_node_cpu_percent" in text
    assert "ray_tpu_node_mem_total_bytes" in text


def test_grafana_dashboard_generation():
    """No cluster needed: the generated dashboard is valid Grafana JSON
    covering the exported metric families (reference:
    grafana_dashboard_factory.py)."""
    import json as _json

    from ray_tpu.dashboard.grafana import dashboard_json, generate_dashboard

    d = generate_dashboard()
    assert d["uid"] == "ray-tpu-cluster"
    assert len(d["panels"]) >= 10
    exprs = " ".join(
        t["expr"] for p in d["panels"] for t in p["targets"]
    )
    for fam in ("ray_tpu_node_resource_total", "ray_tpu_object_store_used",
                "ray_tpu_node_cpu_percent", "ray_tpu_worker_rss_bytes"):
        assert fam in exprs
    _json.loads(dashboard_json())  # serializes cleanly


def test_cli_profile_and_grafana(cluster, tmp_path):
    """Operator CLI: `ray-tpu profile --pid` and `ray-tpu grafana`
    (dogfooding the endpoints from the command line)."""
    from ray_tpu import api, scripts

    @ray_tpu.remote
    class Busy:
        def work(self, s):
            t0 = time.time()
            x = 0
            while time.time() - t0 < s:
                x += sum(i for i in range(50))
            return x

        def pid(self):
            import os

            return os.getpid()

    b = Busy.remote()
    pid = ray_tpu.get(b.pid.remote())
    ref = b.work.remote(5.0)
    time.sleep(0.3)
    out_file = tmp_path / "prof.folded"
    scripts.main([
        "profile", "--address", api._local_node.gcs_address,
        "--pid", str(pid), "--duration", "1.5", "-o", str(out_file),
    ])
    folded = out_file.read_text()
    assert "work" in folded and folded.splitlines()
    ray_tpu.get(ref)

    g_file = tmp_path / "dash.json"
    scripts.main(["grafana", "-o", str(g_file)])
    dash = json.loads(g_file.read_text())
    assert dash["uid"] == "ray-tpu-cluster"
