"""The invariant lint plane (ray_tpu/_private/lint/).

Each rule is exercised against a SYNTHETIC mini-repo (its own contract
files + seeded violations) so the assertions pin exact rule ids and
file:line anchors, independent of the real package's contents; the tier-1
test at the bottom then runs the full linter over the real ray_tpu/ and
asserts zero non-baseline findings — the same gate CI runs.
"""

import json
import os
import textwrap

import pytest

from ray_tpu._private.lint import (
    find_repo_root,
    load_baseline,
    render_report,
    run_lint,
    save_baseline,
)
from ray_tpu._private.lint.core import apply_baseline

REPO_ROOT = find_repo_root(os.path.dirname(os.path.dirname(__file__)))


def _write(root, rel, text):
    path = os.path.join(root, *rel.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(textwrap.dedent(text))
    return path


def make_mini_repo(tmp_path):
    """A synthetic repo with one declared flag/metric/event/site each."""
    root = str(tmp_path / "repo")
    _write(root, "ray_tpu/_private/config.py", '''\
        _FLAGS = {
            "declared_flag": 1,
        }
        ''')
    # a reader for every declared flag, so the default mini repo is clean
    _write(root, "ray_tpu/_read_flags.py", '''\
        from ray_tpu._private.config import RTPU_CONFIG

        DECLARED = RTPU_CONFIG.declared_flag
        ''')
    _write(root, "ray_tpu/util/metrics.py", '''\
        """Contract:
            ray_tpu_registered_total   counter
        """
        ''')
    _write(root, "ray_tpu/_private/flight_recorder.py", '''\
        """Recorder.

        EVENT-NAME STABILITY CONTRACT
        -----------------------------
          good.event   a fine event
        """
        def record(event, a=b"", b=""):
            pass
        ''')
    _write(root, "ray_tpu/_private/chaos.py", '''\
        """Chaos.

        SITE-NAME STABILITY CONTRACT
        ----------------------------
          good.site   a fine site

        THE PLAN
        --------
        (rules...)
        """
        ARMED = False
        def hit(site, **attrs):
            return None
        ''')
    return root


def _rules_at(result, rel):
    return [(f.rule, f.line) for f in result.new if f.path == rel]


# ------------------------------------------------------ contract cross-check


@pytest.mark.fast
def test_flag_undeclared_and_dead(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/_private/config.py", '''\
        _FLAGS = {
            "declared_flag": 1,
            "dead_flag": 2,
        }
        ''')
    _write(root, "ray_tpu/app.py", '''\
        import os
        from ray_tpu._private.config import RTPU_CONFIG

        def f():
            a = RTPU_CONFIG.declared_flag          # ok: declared
            b = RTPU_CONFIG.bogus_flag             # line 6: undeclared
            c = os.environ.get("RTPU_bogus_two")   # line 7: undeclared
            d = os.environ.get("RTPU_ADDRESS")     # ok: infra env (caps)
            return a, b, c, d
        ''')
    r = run_lint(root=root)
    assert _rules_at(r, "ray_tpu/app.py") == [
        ("flag-undeclared", 6),
        ("flag-undeclared", 7),
    ]
    # dead_flag is declared but never read -> anchored at its config line
    dead = [f for f in r.new if f.rule == "flag-dead"]
    assert [f.path for f in dead] == ["ray_tpu/_private/config.py"]
    assert "dead_flag" in dead[0].message
    assert dead[0].line == 3


@pytest.mark.fast
def test_metric_unregistered(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/emit.py", '''\
        from ray_tpu.util.metrics import Counter

        good = Counter("ray_tpu_registered_total")
        bad = Counter("ray_tpu_bogus_total")
        samples = []
        samples.append(("ray_tpu_tuple_metric", {"node": "n"}, 1.0))
        samples.append(("ray_tpu_results", "not-a-labels-dict"))
        other = Counter(some_dynamic_name)
        ''')
    r = run_lint(root=root)
    assert _rules_at(r, "ray_tpu/emit.py") == [
        ("metric-unregistered", 4),
        ("metric-unregistered", 6),
    ]
    assert "ray_tpu_bogus_total" in r.new[0].message or \
        "ray_tpu_bogus_total" in " ".join(f.message for f in r.new)


@pytest.mark.fast
def test_event_and_chaos_site_unregistered(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/events.py", '''\
        from ray_tpu._private import flight_recorder as _fr
        from ray_tpu._private import chaos as _chaos

        def f(name):
            _fr.record("good.event", b"", "fine")
            _fr.record("bogus.event", b"", "nope")
            _fr.record(name)              # dynamic: out of scope
            _chaos.hit("good.site")
            _chaos.hit("bogus.site", x=1)
        ''')
    r = run_lint(root=root)
    assert _rules_at(r, "ray_tpu/events.py") == [
        ("event-unregistered", 6),
        ("chaos-site-unregistered", 9),
    ]


# ---------------------------------------------------------- shard safety


@pytest.mark.fast
def test_shard_safety_rules(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/server_mod.py", '''\
        _SHARD_SAFE_FIELDS = {"stats"}

        class Node:
            def start(self, server):
                server.register_all(self)
                server.set_shard_safe({"Good", "Bad", "Typo"})

            async def handle_Good(self, req):
                with self._lock:
                    self.counter += 1        # locked: fine
                self.stats.append(1)         # allowlisted field: fine
                local = {}
                local["x"] = 1               # not self state: fine
                return {"ok": True}

            async def handle_Bad(self, req):
                self.counter += 1            # line 17: unlocked mutation
                self.pending.append(req)     # line 18: unlocked mutator call
                return {"ok": True}
        ''')
    r = run_lint(root=root)
    got = _rules_at(r, "ray_tpu/server_mod.py")
    assert ("shard-safe-unresolved", 6) in got      # "Typo" never resolves
    assert ("shard-unsafe-mutation", 17) in got
    assert ("shard-unsafe-mutation", 18) in got
    assert len(got) == 3
    unresolved = [f for f in r.new if f.rule == "shard-safe-unresolved"]
    assert "handle_Typo" in unresolved[0].message


@pytest.mark.fast
def test_rpc_choke_point_bypass(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/_private/rpc.py", '''\
        class RpcServer:
            async def _run_handler(self, method, handler, payload):
                return await handler(payload)    # the one legal call site

            async def _dispatch_ok(self, method, payload):
                handler = self._handlers.get(method)
                return await self._run_handler(method, handler, payload)

            async def _dispatch_bad(self, method, payload):
                handler = self._handlers.get(method)
                return await handler(payload)    # line 11: bypasses the hop

            async def _notify_bad(self, method, payload):
                return self._handlers[method](payload)   # line 14: same
        ''')
    r = run_lint(root=root)
    got = _rules_at(r, "ray_tpu/_private/rpc.py")
    assert ("shard-home-loop-bypass", 11) in got
    assert ("shard-home-loop-bypass", 14) in got
    assert len(got) == 2


# ------------------------------------------------------- blocking detector


@pytest.mark.fast
def test_blocking_calls_in_coroutines(tmp_path):
    root = make_mini_repo(tmp_path)
    # inside the package: only control-plane modules are in scope
    _write(root, "ray_tpu/serve/loopmod.py", '''\
        import asyncio
        import subprocess
        import time

        async def bad():
            time.sleep(1)                     # line 6
            subprocess.run(["true"])          # line 7
            open("/tmp/x")                    # line 8
            with lock_thing:                  # line 9: sync lock
                pass

        async def good(sem, loop):
            await asyncio.sleep(0)
            await sem.acquire()               # awaited: fine

            def helper():
                time.sleep(1)                 # sync def: fine (executor)
            await loop.run_in_executor(None, helper)
        ''')
    # same violations OUTSIDE the control-plane scope: ignored
    _write(root, "ray_tpu/train/offloop.py", '''\
        import time

        async def also_sleeps():
            time.sleep(1)
        ''')
    r = run_lint(root=root)
    assert _rules_at(r, "ray_tpu/serve/loopmod.py") == [
        ("blocking-call-in-async", 6),
        ("blocking-call-in-async", 7),
        ("blocking-io-in-async", 8),
        ("sync-lock-in-async", 9),
    ]
    assert _rules_at(r, "ray_tpu/train/offloop.py") == []


@pytest.mark.fast
def test_unawaited_lock_acquire_in_coroutine(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/serve/lockmod.py", '''\
        async def f(self):
            self._lock.acquire()              # line 2: un-awaited
            ok = await self._alock.acquire()  # awaited: fine
            self.queue.get()                  # not lock-ish: fine
            return ok
        ''')
    r = run_lint(root=root)
    assert _rules_at(r, "ray_tpu/serve/lockmod.py") == [
        ("sync-lock-in-async", 2),
    ]


# ------------------------------------------------- pragma + baseline round-trip


@pytest.mark.fast
def test_allow_pragma_suppression(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/serve/pragmod.py", '''\
        import time

        async def f():
            time.sleep(1)  # lint: allow(blocking-call-in-async) -- why
            # lint: allow(blocking-call-in-async) -- pragma on prior line
            time.sleep(2)
            time.sleep(3)  # lint: allow(some-other-rule)
            time.sleep(4)  # lint: allow(*)
        ''')
    r = run_lint(root=root)
    got = _rules_at(r, "ray_tpu/serve/pragmod.py")
    assert got == [("blocking-call-in-async", 7)]  # wrong-rule pragma: kept
    assert r.suppressed == 3


@pytest.mark.fast
def test_baseline_round_trip(tmp_path):
    root = make_mini_repo(tmp_path)
    mod = _write(root, "ray_tpu/serve/basemod.py", '''\
        import time

        async def f():
            time.sleep(1)
        ''')
    r1 = run_lint(root=root)
    assert [f.rule for f in r1.new] == ["blocking-call-in-async"]

    # accept the current findings; a re-run is clean
    bl_path = os.path.join(root, ".lint-baseline.json")
    save_baseline(bl_path, r1.new)
    bl = load_baseline(bl_path)
    r2 = run_lint(root=root, baseline=bl)
    assert r2.ok and len(r2.accepted) == 1

    # a NEW violation fails while the accepted one stays accepted
    with open(mod, "a") as f:
        f.write("\nasync def g():\n    time.sleep(2)\n")
    r3 = run_lint(root=root, baseline=bl)
    assert [f.rule for f in r3.new] == ["blocking-call-in-async"]
    assert "time.sleep(2)" in r3.new[0].snippet
    assert len(r3.accepted) == 1

    # editing the ACCEPTED line re-surfaces its finding for review
    with open(mod, "w") as f:
        f.write("import time\n\nasync def f():\n    time.sleep(1 + 0)\n")
    r4 = run_lint(root=root, baseline=bl)
    assert [f.snippet for f in r4.new] == ["time.sleep(1 + 0)"]
    assert not r4.accepted


@pytest.mark.fast
def test_report_rendering_and_json(tmp_path):
    root = make_mini_repo(tmp_path)
    _write(root, "ray_tpu/serve/rmod.py", '''\
        import time

        async def f():
            time.sleep(1)
        ''')
    r = run_lint(root=root)
    text = render_report(r)
    assert "ray_tpu/serve/rmod.py:4: blocking-call-in-async" in text
    assert text.strip().endswith(")") and "FAIL" in text
    doc = r.to_json()
    assert doc["schema"] == "ray_tpu.lint.v1"
    assert doc["ok"] is False
    assert doc["findings"][0]["rule"] == "blocking-call-in-async"
    json.dumps(doc)  # artifact mode must be serializable


# ------------------------------------------------------------- tier-1 gate


def test_full_package_lint_is_clean():
    """The same gate CI runs: the real linter over the real package with
    the committed baseline must produce zero new findings. If this fails,
    either fix the new violation or (for an accepted design) add an
    inline `# lint: allow(<rule>)` / regenerate the baseline — see the
    rule reference in ray_tpu/_private/lint/__init__.py."""
    bl = load_baseline(os.path.join(REPO_ROOT, ".lint-baseline.json"))
    result = run_lint(root=REPO_ROOT, baseline=bl)
    assert result.files > 100  # sanity: the real package was scanned
    assert result.ok, "new lint findings:\n" + render_report(result)


def test_seeded_violations_all_fire_on_real_contracts(tmp_path):
    """Acceptance sweep: one seeded violation per analyzer, checked
    against the REAL repo contracts (not the mini fixtures), each caught
    with the right rule id and line."""
    fixture = _write(str(tmp_path), "seeded.py", '''\
        import time
        from ray_tpu._private import flight_recorder as _fr
        from ray_tpu._private.config import RTPU_CONFIG
        from ray_tpu.util.metrics import Counter

        flag = RTPU_CONFIG.definitely_not_a_flag          # line 6
        metric = Counter("ray_tpu_never_registered_total")  # line 7

        def emit():
            _fr.record("never.registered")                # line 10

        class Srv:
            def start(self, server):
                server.set_shard_safe({"Mut"})            # line 14

            async def handle_Mut(self, req):
                self.state = req                          # line 17

        async def pump():
            time.sleep(0.1)                               # line 20
        ''')
    r = run_lint(paths=[fixture], root=REPO_ROOT)
    got = {(f.rule, f.line) for f in r.new}
    assert ("flag-undeclared", 6) in got
    assert ("metric-unregistered", 7) in got
    assert ("event-unregistered", 10) in got
    assert ("shard-unsafe-mutation", 17) in got
    assert ("blocking-call-in-async", 20) in got


def test_cli_json_and_exit_codes(tmp_path, capsys):
    """`ray-tpu lint` over the real repo: exit 0 + machine-readable
    report with the committed baseline; exit 1 with --no-baseline (the
    accepted findings exist)."""
    from ray_tpu import scripts

    scripts.main(["lint", "--root", REPO_ROOT, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "ray_tpu.lint.v1" and doc["ok"] is True
    assert doc["accepted_by_baseline"]  # the committed accepted findings

    with pytest.raises(SystemExit) as ei:
        scripts.main(["lint", "--root", REPO_ROOT, "--no-baseline"])
    assert ei.value.code == 1
