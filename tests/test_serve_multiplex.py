"""Model multiplexing tests (reference: python/ray/serve/tests/
test_multiplex.py — LRU model cache per replica, model-id routing)."""

import asyncio

import pytest


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def test_model_cache_lru_eviction():
    """Unit: the LRU cache loads once per id and evicts beyond the cap."""
    from ray_tpu.serve.multiplex import multiplexed

    loads = []

    @multiplexed(max_num_models_per_replica=2)
    async def get_model(model_id):
        loads.append(model_id)
        return f"model-{model_id}"

    async def run():
        assert await get_model("a") == "model-a"
        assert await get_model("b") == "model-b"
        assert await get_model("a") == "model-a"  # cached
        assert loads == ["a", "b"]
        await get_model("c")  # evicts b (LRU)
        assert set(get_model._serve_model_cache.loaded_ids()) == {"a", "c"}
        await get_model("b")  # reload
        assert loads == ["a", "b", "c", "b"]

    asyncio.new_event_loop().run_until_complete(run())


def test_multiplexed_deployment(serve_cluster):
    serve = serve_cluster

    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class ModelServer:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id):
            self.loads.append(model_id)
            return {"id": model_id, "weights": len(model_id)}

        async def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return {"model": model["id"], "out": x * model["weights"]}

    handle = serve.run(ModelServer.bind(), name="mux")
    # same model id must hit the same replica (affinity) and load once
    for _ in range(4):
        r = handle.options(multiplexed_model_id="abc").remote(2).result(
            timeout=30)
        assert r == {"model": "abc", "out": 6}
    r = handle.options(multiplexed_model_id="zz").remote(5).result(timeout=30)
    assert r == {"model": "zz", "out": 10}


def test_get_multiplexed_model_id_in_sync_method(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class Sync:
        def __call__(self, _):
            return serve.get_multiplexed_model_id()

    handle = serve.run(Sync.bind(), name="sync_mux")
    assert handle.options(
        multiplexed_model_id="m7").remote(0).result(timeout=30) == "m7"
    assert handle.remote(0).result(timeout=30) == ""


def test_streaming_response(serve_cluster):
    """Generator deployments stream items incrementally through
    handle.options(stream=True) (reference: serve streaming responses)."""
    serve = serve_cluster

    @serve.deployment
    class Tokens:
        def __call__(self, n):
            for i in range(n):
                yield f"tok{i}"

        async def agen(self, n):
            for i in range(n):
                yield i * i

    handle = serve.run(Tokens.bind(), name="streamer")
    out = list(handle.options(stream=True).remote(40))
    assert out == [f"tok{i}" for i in range(40)]
    # async generator method, separate call
    sq = list(handle.options(stream=True, method_name="agen").remote(5))
    assert sq == [0, 1, 4, 9, 16]
    # non-streaming calls still work on the same deployment
    with pytest.raises(Exception):
        # calling a generator without stream=True returns the generator
        # object which cannot serialize cleanly — streaming must be explicit
        handle.remote(3).result(timeout=10)


def test_http_streaming(serve_cluster):
    """?stream=1 streams generator items as HTTP chunks through the proxy
    (reference: serve streaming responses over HTTP)."""
    import urllib.request

    serve = serve_cluster

    @serve.deployment
    class Gen:
        def __call__(self, n):
            for i in range(int(n)):
                yield {"i": i}

    serve.run(Gen.bind(), name="httpstream", route_prefix="/gen")
    import ray_tpu

    port = ray_tpu.get(
        ray_tpu.get_actor(serve.CONTROLLER_NAME).ensure_proxy.remote(),
        timeout=60,
    )
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/gen?stream=1",
        data=b"5", headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers.get("Transfer-Encoding") == "chunked"
        lines = [ln for ln in r.read().decode().splitlines() if ln]
    import json as _json

    assert [_json.loads(ln)["i"] for ln in lines] == [0, 1, 2, 3, 4]
