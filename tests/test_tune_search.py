"""Searcher framework (reference: tune/search/): pluggable suggest/
feedback protocol, native TPE, concurrency limiting."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    ConcurrencyLimiter,
    Searcher,
    TPESearcher,
    TuneConfig,
    Tuner,
)


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _objective(config):
    from ray_tpu import train

    # quadratic bowl: best at x=0.3, y='b'
    score = -((config["x"] - 0.3) ** 2) + (0.5 if config["y"] == "b" else 0.0)
    train.report({"score": score})


def test_tpe_beats_pure_random_on_average(cluster):
    space = {"x": tune.uniform(-2.0, 2.0), "y": tune.choice(["a", "b", "c"])}
    tuner = Tuner(
        _objective,
        param_space=space,
        tune_config=TuneConfig(
            metric="score", mode="max", num_samples=24,
            search_alg=ConcurrencyLimiter(
                TPESearcher(n_initial=6, seed=1), max_concurrent=4
            ),
        ),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="score", mode="max")
    # near-optimal x found (pure random expectation over 24 draws on
    # [-2,2] leaves E[min (x-0.3)^2] ~ 0.007; TPE should do better or
    # comparable — the hard assert is concentration below)
    assert best.metrics["score"] > -0.05, best.metrics
    # late trials concentrate near the optimum: the searcher actually
    # used feedback (pure random keeps E|x-0.3| ~ 1.03 over x)
    xs = [r.config["x"] for r in list(grid)[12:]]
    assert float(np.mean(np.abs(np.asarray(xs) - 0.3))) < 0.75, xs


def test_custom_searcher_plugin(cluster):
    """The Searcher seam works for user-defined algorithms."""

    class FixedSequence(Searcher):
        def __init__(self, seq):
            super().__init__()
            self._seq = list(seq)
            self.completed = []

        def suggest(self, trial_id):
            return self._seq.pop(0) if self._seq else None

        def on_trial_complete(self, trial_id, result=None, error=False):
            self.completed.append((trial_id, result and result.get("score")))

    searcher = FixedSequence([{"x": 0.0, "y": "a"}, {"x": 0.3, "y": "b"}])
    tuner = Tuner(
        _objective,
        param_space={},
        tune_config=TuneConfig(
            metric="score", mode="max", num_samples=2, search_alg=searcher
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert len(searcher.completed) == 2
    best = grid.get_best_result(metric="score", mode="max")
    assert abs(best.config["x"] - 0.3) < 1e-9


def test_grid_rejected_by_tpe():
    with pytest.raises(ValueError, match="grid_search"):
        TPESearcher().set_search_properties(
            "score", "max", {"x": tune.grid_search([1, 2])}
        )
