"""Fast tier: every core path smoke-checked against ONE shared cluster.

`pytest -m fast` is the inner verify loop (reference: the size/tags
discipline in python/ray/tests/BUILD:18 — small tests gate every change,
the full suite gates merges). One module-scoped 2-node cluster amortizes
the boot cost across all probes, so the whole tier runs in ~1-2 minutes
on a 1-core box where the 297-test suite takes >10.

Covers: tasks (plain/nested/errors), objects (inline + plasma + wait),
actors (create/call/named/kill), placement groups, multi-node spread,
runtime_env env_vars, collectives rendezvous, and a jit'd sharded
train step on the virtual CPU mesh.
"""

import time

import numpy as np
import pytest

import ray_tpu

pytestmark = pytest.mark.fast


@pytest.fixture(scope="module")
def fast_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_task_roundtrip(fast_cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3
    # fan-out + nested refs as args
    refs = [add.remote(i, i) for i in range(8)]
    assert ray_tpu.get(add.remote(refs[0], refs[1])) == 2
    assert ray_tpu.get(refs) == [2 * i for i in range(8)]


def test_task_error_propagates(fast_cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("fast-tier-boom")

    with pytest.raises(Exception, match="fast-tier-boom"):
        ray_tpu.get(boom.remote())


def test_objects_inline_and_plasma(fast_cluster):
    small = ray_tpu.put({"k": 1})
    big = ray_tpu.put(np.arange(300_000, dtype=np.float64))  # > inline cap
    assert ray_tpu.get(small) == {"k": 1}
    assert float(ray_tpu.get(big).sum()) == float(np.arange(300_000).sum())

    @ray_tpu.remote
    def total(arr):
        return float(arr.sum())

    assert ray_tpu.get(total.remote(big)) == float(np.arange(300_000).sum())


def test_wait_semantics(fast_cluster):
    @ray_tpu.remote
    def slow(x):
        time.sleep(x)
        return x

    fast_ref = slow.remote(0.0)
    slow_ref = slow.remote(5.0)
    ready, not_ready = ray_tpu.wait([fast_ref, slow_ref], num_returns=1,
                                    timeout=30)
    assert ready == [fast_ref] and not_ready == [slow_ref]


def test_actor_lifecycle(fast_cluster):
    @ray_tpu.remote(num_cpus=0.01)
    class Counter:
        def __init__(self, v=0):
            self.v = v

        def inc(self):
            self.v += 1
            return self.v

    actors = [Counter.remote(i) for i in range(6)]
    assert ray_tpu.get([a.inc.remote() for a in actors]) == [
        i + 1 for i in range(6)
    ]
    named = Counter.options(name="fast_counter").remote(10)
    assert ray_tpu.get(named.inc.remote()) == 11
    h = ray_tpu.get_actor("fast_counter")
    assert ray_tpu.get(h.inc.remote()) == 12
    for a in actors:
        ray_tpu.kill(a)
    ray_tpu.kill(named)  # release its CPU so the quiesce check can reach 4.0


def test_placement_group(fast_cluster):
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    pg = placement_group([{"CPU": 0.5}, {"CPU": 0.5}], strategy="PACK")
    pg.ready()

    @ray_tpu.remote(num_cpus=0.5)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    n = ray_tpu.get(
        where.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
        ).remote()
    )
    assert isinstance(n, str) and len(n) > 0
    remove_placement_group(pg)


def test_multi_node_spread(fast_cluster):
    # Quiesce first: stragglers from earlier probes (the wait test's slow
    # task) skew placement and make the spill assertion flaky.
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) >= 4.0:
            break
        time.sleep(0.5)

    @ray_tpu.remote(num_cpus=1)
    def node_of():
        time.sleep(2)  # hold the CPU so the tasks must run concurrently
        return ray_tpu.get_runtime_context().get_node_id()

    # 6 concurrent 1-CPU tasks must spill across both 2-CPU nodes
    nodes = set(ray_tpu.get([node_of.remote() for _ in range(6)]))
    assert len(nodes) == 2, nodes


def test_runtime_env_env_vars(fast_cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"FAST_TIER_VAR": "yes"}})
    def read_env():
        import os

        return os.environ.get("FAST_TIER_VAR")

    assert ray_tpu.get(read_env.remote()) == "yes"


def test_train_step_sharded():
    """Compiled sharded train step on the virtual 8-device CPU mesh —
    the compute-path smoke (no cluster needed)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    mesh = make_mesh({"dp": 2, "fsdp": 2, "sp": 1, "tp": 2})
    cfg = GPT2Config(
        vocab_size=128, block_size=32, n_layer=2, n_head=4, n_embd=32,
        dtype=jnp.float32, use_flash_attention=False,
    )
    ts = TrainStep(cfg, mesh, learning_rate=1e-3)
    state = ts.init(jax.random.PRNGKey(0))
    idx = jnp.zeros((8, 32), dtype=jnp.int32)
    batch = ts.shard_batch({"idx": idx, "targets": idx})
    state, metrics = ts.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
