"""Mixture-of-Experts + expert parallelism (green-field; no reference
counterpart — SURVEY §2.4 lists EP/MoE as absent upstream).

Covers: routing/capacity semantics, parity with a dense FFN when all
experts are identical, the Switch load-balance loss, and a sharded
end-to-end training step on an 8-device dp x ep mesh with the experts'
leading dim partitioned over 'ep'.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_top_k_routing_capacity_and_weights():
    from ray_tpu.ops.moe import top_k_routing

    B, S, E, cap = 1, 4, 2, 2
    # All tokens prefer expert 0 strongly.
    probs = jnp.tile(jnp.array([0.9, 0.1], jnp.float32), (B, S, 1))
    dispatch, combine = top_k_routing(probs, k=1, capacity=cap)
    # Expert 0 admits only `cap` tokens (earliest positions win)...
    assert float(dispatch[0, :, 0].sum()) == cap
    assert float(dispatch[0, 0, 0].sum()) == 1.0
    assert float(dispatch[0, 1, 0].sum()) == 1.0
    # ...and the overflowing tokens are dropped entirely (k=1).
    assert float(dispatch[0, 2].sum()) == 0.0
    assert float(dispatch[0, 3].sum()) == 0.0
    # top-1 combine weights are renormalized to 1 for admitted tokens.
    assert np.isclose(float(combine[0, 0].sum()), 1.0)

    # k=2 with generous capacity: every token reaches both experts and the
    # combine weights sum to 1.
    dispatch, combine = top_k_routing(probs, k=2, capacity=S)
    assert np.allclose(np.asarray(dispatch.sum(axis=(2, 3))), 2.0)
    assert np.allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0, atol=1e-6)


def test_moe_matches_dense_when_experts_identical():
    """With identical experts and k=1, routing is irrelevant: the MoE layer
    must reproduce the plain FFN."""
    from ray_tpu.ops.moe import MoE, MoEConfig

    B, S, C, F, E = 2, 8, 16, 32, 4
    layer = MoE(
        d_model=C, d_ff=F,
        moe=MoEConfig(num_experts=E, top_k=1, capacity_factor=float(E)),
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, C), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    w1 = np.asarray(params["wi"][0])
    w2 = np.asarray(params["wo"][0])
    params["wi"] = jnp.tile(w1[None], (E, 1, 1))
    params["wo"] = jnp.tile(w2[None], (E, 1, 1))

    out, _ = layer.apply({"params": params}, x, mutable=["losses"])
    import flax.linen as nn

    expect = np.asarray(nn.gelu(x @ w1, approximate=True) @ w2)
    assert np.allclose(np.asarray(out), expect, atol=1e-4)


def test_load_balance_loss_uniform_is_one():
    from ray_tpu.ops.moe import load_balance_loss, top_k_routing

    B, S, E = 2, 16, 4
    probs = jnp.full((B, S, E), 1.0 / E, jnp.float32)
    # Break argmax ties deterministically with a tiny tilt per token.
    tilt = jax.random.uniform(jax.random.PRNGKey(0), (B, S, E)) * 1e-4
    dispatch, _ = top_k_routing(probs + tilt, k=1, capacity=S)
    loss = float(load_balance_loss(probs, dispatch))
    assert 0.8 < loss < 1.3  # ~1.0 for uniform routing


def test_trainstep_with_moe_config_on_ep_mesh():
    """The product TrainStep accepts a GPT2MoEConfig: dp=2 x ep=2 x tp=2
    mesh, experts sharded over 'ep', loss (incl. routed aux) decreases."""
    from ray_tpu.models.gpt2_moe import GPT2MoEConfig
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    cfg = GPT2MoEConfig.tiny_moe(dtype=jnp.float32, use_flash_attention=False)
    mesh = make_mesh({"dp": 2, "fsdp": 1, "sp": 1, "tp": 2, "ep": 2})
    ts = TrainStep(cfg, mesh, learning_rate=1e-3)
    state = ts.init(jax.random.PRNGKey(0))

    wi_sharding = state["params"]["h_0"]["moe"]["wi"].sharding
    assert "ep" in (wi_sharding.spec[0] or ()), wi_sharding.spec

    rng = np.random.default_rng(0)
    idx = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    batch = ts.shard_batch({"idx": idx, "targets": np.roll(idx, -1, axis=1)})
    losses = []
    for _ in range(4):
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_moe_model_trains_on_dp_ep_mesh():
    """8 virtual devices as dp=2 x ep=4: one full fwd/bwd/update step of the
    MoE transformer with experts sharded over 'ep', and sharded forward
    matches the unsharded forward."""
    import optax

    from ray_tpu.models.gpt2_moe import (
        GPT2MoEConfig,
        GPT2_MOE_SHARDING_RULES,
        forward_with_aux,
        init_params,
        moe_loss_fn,
    )
    from ray_tpu.parallel.mesh import make_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2MoEConfig.tiny_moe(dtype=jnp.float32, use_flash_attention=False)
    mesh = make_mesh({"dp": 2, "ep": 4})
    params = init_params(cfg)
    specs = GPT2_MOE_SHARDING_RULES.tree_specs(params)
    # Expert tensors really carry the ep axis.
    assert specs["h_0"]["moe"]["wi"] == P("ep", "fsdp", "tp")

    def prune(spec):
        # Axes absent from this mesh (fsdp/tp here) fall back to replicated.
        return P(*(a if a in mesh.shape else None for a in spec))

    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, prune(s))),
        params,
        specs,
    )

    rng = np.random.default_rng(0)
    idx = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    targets = np.roll(idx, -1, axis=1)
    batch_sharding = NamedSharding(mesh, P("dp", None))
    idx_s = jax.device_put(idx, batch_sharding)
    tgt_s = jax.device_put(targets, batch_sharding)

    # Parity: sharded vs single-device logits.
    logits_ref, aux_ref = forward_with_aux(cfg, params, idx)
    logits_sh, aux_sh = jax.jit(
        lambda p, i: forward_with_aux(cfg, p, i)
    )(sharded, idx_s)
    assert np.allclose(
        np.asarray(logits_sh), np.asarray(logits_ref), atol=2e-3
    )
    assert np.isclose(float(aux_sh), float(aux_ref), atol=1e-4)
    assert float(aux_sh) > 0.0  # aux loss flows

    # One optimizer step under jit on the mesh: loss finite and decreasing
    # over a few steps on a fixed batch.
    opt = optax.adam(1e-3)
    opt_state = opt.init(sharded)

    @jax.jit
    def step(p, o, i, t):
        loss, grads = jax.value_and_grad(
            lambda pp: moe_loss_fn(cfg, pp, i, t)
        )(p)
        updates, o = opt.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    losses = []
    p, o = sharded, opt_state
    for _ in range(4):
        p, o, loss = step(p, o, idx_s, tgt_s)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
