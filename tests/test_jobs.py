"""Job submission (manager/SDK/REST), dashboard API, operator CLI.

Reference contracts: JobManager + JobSupervisor run entrypoints as
subprocesses with cluster address injected and status/logs queryable
(dashboard/modules/job/job_manager.py:57, job_supervisor.py:51,
sdk.py:35); the dashboard serves the state + job REST API
(dashboard/head.py:79); the CLI mirrors `ray status/timeline/job ...`
(scripts/scripts.py).
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def job_cluster():
    import ray_tpu
    from ray_tpu import api

    ray_tpu.init(num_cpus=4)
    yield api._local_node.gcs_address
    ray_tpu.shutdown()


def test_job_lifecycle(job_cluster, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    script = tmp_path / "job_script.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init(address='auto')\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x * 2\n"
        "print('JOB_RESULT', sum(ray_tpu.get([f.remote(i) for i in range(5)])))\n"
    )
    client = JobSubmissionClient()
    sid = client.submit_job(entrypoint=f"{sys.executable} {script}")
    assert sid.startswith("raysubmit_")

    deadline = time.time() + 120
    while time.time() < deadline:
        status = client.get_job_status(sid)
        if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
            break
        time.sleep(0.5)
    assert status == JobStatus.SUCCEEDED, client.get_job_logs(sid)
    assert "JOB_RESULT 20" in client.get_job_logs(sid)
    jobs = client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)


def test_job_failure_and_stop(job_cluster, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(bad) == JobStatus.FAILED:
            break
        time.sleep(0.3)
    assert client.get_job_status(bad) == JobStatus.FAILED
    assert "code 3" in client.get_job_info(bad)["message"]

    sleeper = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(300)'"
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(sleeper) == JobStatus.RUNNING:
            break
        time.sleep(0.3)
    assert client.stop_job(sleeper)
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(sleeper) == JobStatus.STOPPED:
            break
        time.sleep(0.3)
    assert client.get_job_status(sleeper) == JobStatus.STOPPED


def test_dashboard_api_and_rest_jobs(job_cluster, tmp_path):
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    _, port = start_dashboard(job_cluster)
    base = f"http://127.0.0.1:{port}"

    with urllib.request.urlopen(f"{base}/api/cluster", timeout=30) as r:
        cluster = json.loads(r.read())
    assert cluster["nodes"] == 1
    with urllib.request.urlopen(f"{base}/api/nodes", timeout=30) as r:
        nodes = json.loads(r.read())["nodes"]
    assert nodes[0]["state"] == "ALIVE"
    with urllib.request.urlopen(f"{base}/", timeout=30) as r:
        html = r.read().decode()
    assert "ray_tpu" in html and "id=tiles" in html  # live SPA served at /

    client = JobSubmissionClient(base)  # REST transport
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('VIA_REST')\""
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if client.get_job_status(sid) == JobStatus.SUCCEEDED:
            break
        time.sleep(0.3)
    assert client.get_job_status(sid) == JobStatus.SUCCEEDED
    assert "VIA_REST" in client.get_job_logs(sid)


def test_cli(job_cluster, tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", *args],
            capture_output=True, text=True, env=env, timeout=120,
        )

    out = cli("status", "--address", job_cluster)
    assert out.returncode == 0, out.stderr
    assert "1 alive" in out.stdout

    out = cli("nodes", "--address", job_cluster)
    assert out.returncode == 0 and "head=True" in out.stdout

    trace = tmp_path / "t.json"
    out = cli("timeline", "--address", job_cluster, "-o", str(trace))
    assert out.returncode == 0
    json.loads(trace.read_text())  # valid JSON

    out = cli("job", "--address", job_cluster, "submit", "--",
              sys.executable, "-c", "print(40+2)")
    assert out.returncode == 0, out.stderr
    sid = out.stdout.strip().splitlines()[-1]
    deadline = time.time() + 60
    while time.time() < deadline:
        st = cli("job", "--address", job_cluster, "status", sid)
        if st.stdout.strip() in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.5)
    assert st.stdout.strip() == "SUCCEEDED"
    logs = cli("job", "--address", job_cluster, "logs", sid)
    assert "42" in logs.stdout


def test_dashboard_logs_api(job_cluster):
    """Log module (reference: dashboard/modules/log/): list + tail session
    log files over HTTP; path traversal is rejected."""
    from ray_tpu.dashboard import start_dashboard

    _, port = start_dashboard(job_cluster)
    base = f"http://127.0.0.1:{port}"
    with urllib.request.urlopen(f"{base}/api/logs", timeout=30) as r:
        logs = json.loads(r.read())["logs"]
    assert logs, "session log dir should contain process logs"
    name = next(l["name"] for l in logs if l["size_bytes"] > 0)
    with urllib.request.urlopen(f"{base}/api/logs/{name}?tail=5",
                                timeout=30) as r:
        payload = json.loads(r.read())
    assert payload["name"] == name
    assert len(payload["lines"]) <= 5
    # traversal attempt 404s
    try:
        urllib.request.urlopen(f"{base}/api/logs/..%2Fgcs.log", timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
