"""Direct call channel (_private/direct_channel.py): the blocking-socket
fast path for serial sync actor calls, its ordering guarantees across the
loop->channel switch, failure semantics, and fallbacks.

Reference behaviors mirrored: per-caller submission order
(src/ray/core_worker/transport/actor_task_submitter.h), in-flight tasks
failing with ActorDiedError on worker death (actor_task_submitter
ConnectionLost handling)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError, TaskError


def _worker():
    from ray_tpu._private.worker import get_global_worker

    return get_global_worker()


@pytest.mark.fast
def test_sync_calls_ride_the_channel(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    a = A.remote()
    out = [ray_tpu.get(a.bump.remote()) for _ in range(60)]
    assert out == list(range(1, 61))
    stats = _worker()._direct.stats
    # First call(s) establish + switch; the steady state is all-direct.
    assert stats["switches"] == 1
    assert stats["direct_sent"] >= 50
    assert stats["fast_get_hits"] >= 40
    assert stats["channel_deaths"] == 0


@pytest.mark.fast
def test_order_preserved_across_switch_and_bursts(shutdown_only):
    """Tasks posted to the loop path before/while the channel activates must
    execute before later direct sends — the actor records arrival order."""
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class Rec:
        def __init__(self):
            self.seen = []

        def add(self, i):
            self.seen.append(i)
            return i

        def all(self):
            return list(self.seen)

    r = Rec.remote()
    refs = [r.add.remote(i) for i in range(50)]  # burst: loop path pre-switch
    assert ray_tpu.get(r.add.remote(50)) == 50  # sync: may or may not switch
    refs2 = [r.add.remote(51 + i) for i in range(30)]  # burst again
    assert ray_tpu.get(r.add.remote(81)) == 81
    ray_tpu.get(refs + refs2)
    assert ray_tpu.get(r.all.remote()) == list(range(82))


def test_error_replies_and_large_results(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def boom(self):
            raise ValueError("intentional")

        def big(self):
            return np.arange(1_000_000)  # > inline threshold -> plasma

        def ok(self):
            return 7

    a = A.remote()
    for _ in range(5):  # activate the channel
        assert ray_tpu.get(a.ok.remote()) == 7
    assert _worker()._direct.stats["switches"] == 1
    with pytest.raises((TaskError, ValueError)):
        ray_tpu.get(a.boom.remote())
    # Plasma-bound result through the direct channel: reply defers to the
    # io loop, the fast get falls back, and the value still round-trips.
    np.testing.assert_array_equal(ray_tpu.get(a.big.remote()),
                                  np.arange(1_000_000))
    assert ray_tpu.get(a.ok.remote()) == 7


def test_ref_args_resolve_on_the_direct_path(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def produce():
        return 21

    @ray_tpu.remote
    class A:
        def double(self, x):
            return 2 * x

        def ok(self):
            return 1

    a = A.remote()
    for _ in range(5):
        ray_tpu.get(a.ok.remote())
    ref = produce.remote()
    assert ray_tpu.get(a.double.remote(ref)) == 42
    # big arg -> promoted to plasma ref at submit, resolved worker-side
    big = np.ones(500_000)
    assert ray_tpu.get(a.double.remote(big)).sum() == 1_000_000


def test_actor_death_fails_inflight_direct_tasks(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def ok(self):
            return 1

        def slow(self):
            time.sleep(30)
            return 2

    a = A.remote()
    for _ in range(5):
        ray_tpu.get(a.ok.remote())
    assert _worker()._direct.stats["switches"] == 1
    slow_ref = a.slow.remote()  # occupies the channel
    time.sleep(0.3)
    ray_tpu.kill(a)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(slow_ref, timeout=30)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(a.ok.remote(), timeout=30)
    assert _worker()._direct.stats["channel_deaths"] >= 1


def test_get_timeout_on_direct_pending(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def ok(self):
            return 1

        def slow(self):
            time.sleep(8)
            return 2

    a = A.remote()
    for _ in range(5):
        ray_tpu.get(a.ok.remote())
    t0 = time.monotonic()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(a.slow.remote(), timeout=0.5)
    assert time.monotonic() - t0 < 3.0


def test_disabled_by_config(shutdown_only, monkeypatch):
    monkeypatch.setenv("RTPU_direct_channels", "0")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def ok(self):
            return 1

    a = A.remote()
    for _ in range(10):
        assert ray_tpu.get(a.ok.remote()) == 1
    assert _worker()._direct is None


def test_async_actors_keep_the_loop_path(shutdown_only):
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class Aio:
        async def ok(self):
            return 5

    a = Aio.remote()
    for _ in range(10):
        assert ray_tpu.get(a.ok.remote()) == 5
    w = _worker()
    assert w._direct.stats["switches"] == 0
    assert a._actor_id in w._direct.unavailable
