"""IoThread debug-mode watchdog (the asyncio runtime's sanitizer analogue,
SURVEY.md §5 'sanitizers' — here: blocked-io-loop detection)."""

import subprocess
import sys


def test_watchdog_detects_blocked_loop():
    code = r"""
import asyncio, time
from ray_tpu._private.rpc import IoThread

io = IoThread.current()

async def block():
    time.sleep(1.2)  # sync sleep ON the loop: the bug class we detect

io.run(block())
time.sleep(0.5)
print("done")
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        env={"RTPU_DEBUG_LOOP_MS": "50", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": "/root/repo"},
    )
    assert "done" in proc.stdout
    assert "io loop blocked" in proc.stderr


def test_no_watchdog_noise_when_healthy():
    code = r"""
import asyncio, time
from ray_tpu._private.rpc import IoThread

io = IoThread.current()

async def ok():
    await asyncio.sleep(1.0)  # async sleep: loop keeps ticking

io.run(ok())
print("done")
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        env={"RTPU_DEBUG_LOOP_MS": "50", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": "/root/repo"},
    )
    assert "done" in proc.stdout
    assert "io loop blocked" not in proc.stderr
