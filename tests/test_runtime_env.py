"""runtime_env (env_vars + working_dir) and log_to_driver.

Reference contracts: runtime_env env_vars/working_dir are applied before
user code runs, workers with different envs never share a process
(python/ray/_private/runtime_env/, worker_pool runtime_env_hash), and
worker stdout/stderr stream to the driver via per-node log monitors
(python/ray/_private/log_monitor.py:103).
"""

import os
import time

import pytest


def test_env_vars_applied_and_isolated(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def read_env():
        return os.environ.get("RTPU_TEST_FLAG"), os.getpid()

    val, pid_plain = ray_tpu.get(read_env.remote())
    assert val is None

    with_env = read_env.options(
        runtime_env={"env_vars": {"RTPU_TEST_FLAG": "on"}}
    )
    val, pid_env = ray_tpu.get(with_env.remote())
    assert val == "on"
    assert pid_env != pid_plain  # different env -> different worker process

    # Plain tasks keep running in unpolluted workers.
    val, _ = ray_tpu.get(read_env.remote())
    assert val is None


def test_working_dir(ray_start_regular, tmp_path):
    import ray_tpu

    (tmp_path / "rt_env_probe_mod.py").write_text("MAGIC = 'from-working-dir'\n")
    (tmp_path / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def probe():
        import rt_env_probe_mod  # importable because cwd/sys.path = working_dir

        with open("data.txt") as f:
            payload = f.read()
        return rt_env_probe_mod.MAGIC, payload, os.getcwd()

    magic, payload, cwd = ray_tpu.get(probe.remote())
    assert magic == "from-working-dir"
    assert payload == "payload"
    # The worker runs from the *extracted* copy under the session dir, not
    # the driver's original path (multi-node semantics).
    assert "runtime_envs" in cwd


def test_actor_runtime_env(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "yes"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = A.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"


def test_unsupported_runtime_env_field_rejected(ray_start_regular):
    import ray_tpu

    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.options(runtime_env={"conda": {"dependencies": []}}).remote()


def test_log_to_driver(shutdown_only, capfd):
    import ray_tpu

    ray_tpu.init(num_cpus=2, log_to_driver=True)

    @ray_tpu.remote
    def shout():
        print("HELLO_FROM_WORKER_STDOUT", flush=True)
        return 1

    assert ray_tpu.get(shout.remote()) == 1
    # The node's log monitor tails the worker's log and the driver relays
    # it with a (pid=, ip=) prefix. Poll: tail period is 250ms.
    deadline = time.time() + 20
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().out
        if "HELLO_FROM_WORKER_STDOUT" in seen:
            break
        time.sleep(0.25)
    assert "HELLO_FROM_WORKER_STDOUT" in seen
    assert "(pid=" in seen
