"""IMPALA: async decoupled sampling + v-trace learner (reference:
rllib/algorithms/impala/ — threshold learning test like the PPO one)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import IMPALAConfig


@pytest.fixture(scope="module")
def rl_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_vtrace_reduces_to_gae_free_onpolicy():
    """With pi == mu (rho = c = 1), v-trace targets reduce to n-step TD(lambda=1)
    returns; check against a plain discounted-return rollup on a toy sequence."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core.impala_learner import vtrace

    T, N = 5, 3
    rng = np.random.default_rng(0)
    rewards = jnp.asarray(rng.normal(size=(T, N)).astype(np.float32))
    values = jnp.asarray(rng.normal(size=(T, N)).astype(np.float32))
    boot = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
    gamma = 0.9
    discounts = jnp.full((T, N), gamma)
    ones = jnp.ones((T, N))
    vs, pg_adv = vtrace(ones, rewards, discounts, values, boot, ones)

    # reference: vs_t = r_t + gamma * vs_{t+1}, vs_T = r_T + gamma * boot
    expect = np.zeros((T, N), np.float32)
    acc = np.asarray(boot)
    for t in reversed(range(T)):
        acc = np.asarray(rewards[t]) + gamma * acc
        expect[t] = acc
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-5)
    # pg advantage at on-policy: r + gamma*vs_{t+1} - V(t)
    vs_next = np.concatenate([expect[1:], np.asarray(boot)[None]], 0)
    np.testing.assert_allclose(
        np.asarray(pg_adv),
        np.asarray(rewards) + gamma * vs_next - np.asarray(values),
        rtol=1e-4, atol=1e-5,
    )


def test_trajectory_sampler_shapes(rl_cluster):
    from ray_tpu.rllib.core.rl_module import ActorCriticModule
    from ray_tpu.rllib.env.env_runner import EnvRunnerGroup

    group = EnvRunnerGroup("CartPole-v1", num_runners=1,
                           num_envs_per_runner=3, gamma=0.99, lambda_=1.0)
    obs_dim, num_actions = group.obs_and_action_dims()
    import jax

    params = jax.tree.map(
        np.asarray, ActorCriticModule(num_actions=2).init_params(obs_dim)
    )
    batch = ray_tpu.get(
        group.runners[0].sample_trajectory.remote(params, 16)
    )
    assert batch["obs"].shape == (16, 3, 4)
    assert batch["behavior_logp"].shape == (16, 3)
    assert batch["bootstrap_obs"].shape == (3, 4)
    group.shutdown()


def test_impala_cartpole_learns(rl_cluster):
    """Learning threshold on CartPole with the async engine: decoupled
    runners + continuous v-trace updates on the 8-device mesh learner."""
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=4, num_envs_per_env_runner=8,
                     rollout_fragment_length=32)
        .training(lr=3e-3, entropy_coeff=0.01, train_iter_env_steps=6144)
        .debugging(seed=0)
        .build()
    )
    try:
        assert algo.num_devices() == 8
        best = 0.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 150:
                break
        assert best >= 150, f"IMPALA failed to learn CartPole: best={best:.1f}"
        assert result["learner/learner_env_steps_per_s"] > 0
        # async engine actually decoupled: more learner updates than
        # training iterations x runners would allow synchronously
        assert result["num_learner_updates"] >= result["training_iteration"]
    finally:
        algo.stop()


def test_impala_save_restore(rl_cluster):
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=8)
        .training(train_iter_env_steps=32)
        .build()
    )
    try:
        algo.train()
        path = algo.save()
        w0 = algo.get_weights()
        from ray_tpu.rllib import IMPALA

        algo2 = IMPALA.from_checkpoint(path)
        try:
            w1 = algo2.get_weights()
            import jax

            for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(w1)):
                np.testing.assert_array_equal(a, b)
        finally:
            algo2.stop()
    finally:
        algo.stop()
