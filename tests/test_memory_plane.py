"""Memory observability plane: object ownership ledger, leak detection,
OOM forensics, and the `ray-tpu memory` surfaces.

Contracts under test:
  - the ReferenceCounter ledger records size/callsite/owner-task/pin-state
    per owned ref, pull-only, and the per-entry cost stays inside the same
    tier-1 budget the flight recorder honors (<3.3 µs);
  - `state.memory_report` joins every raylet's plasma/pin/spill tables
    with worker+driver ownership ledgers, and `memory_rollup` folds it
    per job/actor/node unifying plasma bytes, RSS and HBM;
  - a seeded leak (pinned primary whose owner ref was dropped without the
    free path running) raises exactly ONE `object_leak` incident with
    job/callsite attribution, after the two-sweep cross-check;
  - a SIGKILLed actor's death report carries its final memory snapshot
    (top holders), via the periodic on-disk snapshot the raylet reads;
  - `ray-tpu memory` / `--leaks` / `status` / `timeline` object instants
    render from the same aggregation path (tier-1 CLI smoke).
"""

import contextlib
import io
import os
import signal
import time
import types

import pytest

from ray_tpu._private import memory_report as mr
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.reference_counter import ReferenceCounter


# ------------------------------------------------------------- unit: ledger


@pytest.mark.fast
def test_ledger_tracks_metadata_and_frees():
    freed = []
    rc = ReferenceCounter(freed.append)
    oid = ObjectID(b"a" * 20)
    rc.add_owned(oid, size=100, callsite="user.py:7", task_id=b"t1")
    rc.add_local_ref(oid)
    rc.note_size(oid, 4096, plasma=True)
    rows = rc.ledger()
    assert len(rows) == 1
    row = rows[0]
    assert row["size"] == 4096 and row["plasma"] is True
    assert row["callsite"] == "user.py:7" and row["task_id"] == b"t1"
    assert row["age_s"] >= 0.0 and row["local_refs"] == 1
    assert rc.owned_bytes() == (4096, 4096)
    assert rc.owns_many([oid, ObjectID(b"b" * 20)]) == [True, False]
    # the free path drops the ledger entry with the ref
    rc.remove_local_ref(oid)
    assert freed == [oid]
    assert rc.ledger() == [] and rc.owned_bytes() == (0, 0)


@pytest.mark.fast
def test_ledger_limit_keeps_top_holders():
    rc = ReferenceCounter(lambda _: None)
    for i in range(10):
        rc.add_owned(ObjectID(bytes([i]) * 20), size=i * 100)
    rows = rc.ledger(limit=3)
    assert [r["size"] for r in rows] == [900, 800, 700]


@pytest.mark.fast
def test_ledger_overhead_bound():
    """Tier-1 guard: the ledger must not add hot-path cost beyond what
    reference_counter already pays. Budget mirrors the flight recorder's
    (<3.3 µs/event for a 2%-of-small-task envelope); add_owned with full
    metadata plus note_size stays well under it."""
    rc = ReferenceCounter(lambda _: None)
    ids = [ObjectID(os.urandom(20)) for _ in range(2000)]
    n = 50_000
    t0 = time.perf_counter()
    for i in range(n):
        oid = ids[i % 2000]
        rc.add_owned(oid, size=1024, callsite="task:bench", task_id=b"t")
        rc.note_size(oid, 2048, plasma=True)
    per_op = (time.perf_counter() - t0) / (2 * n)
    assert per_op < 3.3e-6, (
        f"ledger write costs {per_op * 1e6:.2f} µs/op — over the hot-path "
        "budget")
    # pull-only: building the report does not mutate the ledger
    before = rc.stats()
    rc.ledger(limit=10)
    assert rc.stats() == before


@pytest.mark.fast
def test_callsite_capture_and_toggle(monkeypatch):
    def user_frame():
        return mr.callsite()

    site = user_frame()
    assert site.startswith("test_memory_plane.py:"), site
    monkeypatch.setenv("RTPU_memory_ledger_callsite", "0")
    assert user_frame() == ""


@pytest.mark.fast
def test_snapshot_roundtrip_and_rendering(tmp_path):
    rc = ReferenceCounter(lambda _: None)
    rc.add_owned(ObjectID(b"c" * 20), size=1 << 20, callsite="hoard.py:3")
    rc.note_size(ObjectID(b"c" * 20), 1 << 20, plasma=True)
    core = types.SimpleNamespace(
        refs=rc,
        worker_id=types.SimpleNamespace(binary=lambda: b"w" * 16),
        actor_id=b"a" * 16,
        job_id=types.SimpleNamespace(binary=lambda: b"j" * 4),
        mode="worker",
        memory_store=types.SimpleNamespace(size=lambda: 2),
        session_dir=str(tmp_path),
    )
    os.makedirs(tmp_path / "logs", exist_ok=True)
    assert mr.write_snapshot(core, top_n=5)
    snap = mr.read_snapshot(str(tmp_path), os.getpid())
    assert snap is not None
    assert snap["owned_plasma_bytes"] == 1 << 20
    assert snap["ledger"][0]["callsite"] == "hoard.py:3"
    text = mr.format_top_holders(snap)
    assert "1.0MiB" in text and "hoard.py:3" in text and "rss=" in text
    # stale snapshots are rejected when an age bound is given
    assert mr.read_snapshot(str(tmp_path), os.getpid(), max_age_s=1e-9) is None


# ------------------------------------------------------------ unit: rollups


def _synthetic_report():
    return {
        "nodes": [
            {
                "node_id": "n1",
                "plasma": {"used_bytes": 500, "capacity_bytes": 1000},
                "pinned_bytes": 300, "pinned_count": 1,
                "spilled_bytes": 0, "spilled_count": 0,
                "raylet_rss": 10, "agent_rss": 0,
                "leaks": [{"object_id": "aa", "size": 50,
                           "job_id": "j1", "actor_id": "", "node_id": "n1"}],
                "leak_candidates": 1,
                "objects": [
                    {"object_id": "o1", "size": 300, "pinned": True,
                     "spilled": False, "job_id": "j1", "actor_id": "ac1"},
                    {"object_id": "o2", "size": 200, "pinned": False,
                     "spilled": True, "job_id": "j2", "actor_id": ""},
                ],
                "workers": [
                    {"worker_id": "w" * 40, "job_id": "j1",
                     "actor_id": "ac1", "rss_bytes": 111,
                     "owned_bytes": 300, "ledger": []},
                ],
            }
        ],
        "drivers": [
            {"worker_id": "d" * 40, "job_id": "j1", "actor_id": "",
             "rss_bytes": 77, "owned_bytes": 5, "ledger": []},
        ],
        "hbm": [
            {"name": "ray_tpu_train_hbm_bytes_in_use", "value": 1000,
             "labels": {"JobId": "j1", "WorkerId": "w" * 12}},
        ],
    }


@pytest.mark.fast
def test_memory_rollup_group_bys():
    from ray_tpu.util.state import memory_rollup

    report = _synthetic_report()
    by_job = memory_rollup(report, "job")
    assert by_job["j1"]["plasma_bytes"] == 300
    assert by_job["j1"]["leaked_bytes"] == 50
    assert by_job["j1"]["rss_bytes"] == 111 + 77  # worker + driver
    assert by_job["j1"]["hbm_bytes"] == 1000
    assert by_job["j2"]["spilled_bytes"] == 200
    by_actor = memory_rollup(report, "actor")
    assert by_actor["ac1"]["plasma_bytes"] == 300
    assert by_actor["ac1"]["hbm_bytes"] == 1000  # WorkerId -> actor mapping
    assert by_actor["-"]["spilled_bytes"] == 200
    by_node = memory_rollup(report, "node")
    assert by_node["n1"]["plasma_bytes"] == 300
    assert by_node["n1"]["objects"] == 2
    assert by_node["(driver)"]["rss_bytes"] == 77
    with pytest.raises(ValueError):
        memory_rollup(report, "nope")


@pytest.mark.fast
def test_timeline_flight_instants():
    from ray_tpu._private.timeline import flight_instant_events

    events = [
        {"seq": 1, "ts": 100.0, "event": "obj.spill", "a": "ab" * 10,
         "b": 4096},
        {"seq": 2, "ts": 101.0, "event": "obj.restore", "a": "ab" * 10,
         "b": 4096},
        {"seq": 3, "ts": 102.0, "event": "obj.leak", "a": "cd" * 10,
         "b": 128},
        {"seq": 4, "ts": 103.0, "event": "lease.grant", "a": "", "b": ""},
    ]
    out = flight_instant_events("deadbeef1234", events)
    assert [e["name"] for e in out] == ["obj.spill", "obj.restore",
                                       "obj.leak"]
    for e in out:
        assert e["ph"] == "i" and e["pid"] == "node:deadbeef"
        assert e["tid"] == "object_store"
    assert out[0]["ts"] == 100.0 * 1e6
    assert out[0]["args"]["object_id"] == "ab" * 10


# --------------------------------------------------- cluster: report + CLI


def test_memory_report_rollups_and_cli_smoke(shutdown_only):
    """Tier-1 `ray-tpu memory` smoke + live rollup/attribution checks."""
    import numpy as np

    import ray_tpu
    from ray_tpu import scripts
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)
    addr = worker_mod.global_worker.gcs_address
    job_hex = worker_mod.global_worker.job_id.hex()

    big = ray_tpu.put(np.zeros(300_000, dtype=np.uint8))  # plasma-bound

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.refs = []

        def hoard(self):
            self.refs.append(ray_tpu.put(np.ones(200_000, dtype=np.uint8)))
            return True

    h = Holder.remote()
    assert ray_tpu.get(h.hoard.remote())

    report = state.memory_report(addr)
    assert len(report["nodes"]) == 1
    node = report["nodes"][0]
    assert node["pinned_count"] >= 2
    assert node["plasma"]["used_bytes"] >= 500_000
    # objects carry pin-meta attribution: job id + callsite
    objs = {o["object_id"]: o for o in node["objects"]}
    mine = objs[big.object_id().hex()]
    assert mine["job_id"] == job_hex
    assert mine["callsite"].startswith("test_memory_plane.py:")
    # the actor's put is attributed to the actor worker in its ledger
    actor_rows = [
        row for w in node["workers"] if w.get("actor_id")
        for row in w["ledger"] if row["plasma"]
    ]
    assert actor_rows, "actor ledger should hold its plasma put"
    # driver ledger reaches the report too
    assert any(
        row["object_id"] == big.object_id().hex()
        for d in report["drivers"] for row in d["ledger"]
    )
    # rollups: job view unifies plasma + rss; actor view splits the actor
    by_job = state.memory_rollup(report, "job")
    assert by_job[job_hex]["plasma_bytes"] >= 500_000
    assert by_job[job_hex]["rss_bytes"] > 0
    by_actor = state.memory_rollup(report, "actor")
    assert any(k not in ("-", "(driver)", "?") and v["plasma_bytes"] > 0
               for k, v in by_actor.items())

    # ---- CLI smoke: memory (all group-bys), --leaks, status ----
    class Args:
        address = addr
        group_by = "job"
        sort_by = "size"
        leaks = False

    for group in ("job", "actor", "node"):
        a = Args()
        a.group_by = group
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            scripts.cmd_memory(a)
        out = buf.getvalue()
        assert "object store" in out and f"by {group}:" in out, out
        assert "top owned objects" in out
        assert "test_memory_plane.py:" in out  # callsites surface in the CLI
    a = Args()
    a.leaks = True
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        scripts.cmd_memory(a)
    assert "no leaked objects" in buf.getvalue()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        scripts.cmd_status(Args())
    out = buf.getvalue()
    assert "object store:" in out and "top job:" in out, out
    ray_tpu.shutdown()


def test_worker_memory_report_rpc_limit(shutdown_only):
    """The worker-side RPC caps ledger rows at the requested top-N."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    ray_tpu.init(num_cpus=1)
    core = worker_mod.global_worker
    refs = [ray_tpu.put(i) for i in range(8)]
    reply = core.io.run(core.handle_GetMemoryReport({"limit": 3}))
    report = reply["report"]
    assert len(report["ledger"]) == 3
    assert report["owned_refs"] >= 8
    assert report["rss_bytes"] > 0
    # CheckRefs: owned vs freed
    oid = refs[0].object_id().binary()
    reply = core.io.run(core.handle_CheckRefs(
        {"ids": [oid, b"\x00" * 20]}))
    assert reply["owned"] == [True, False]
    del refs
    ray_tpu.shutdown()


# ------------------------------------------------------ cluster: leaks


def test_leak_detector_two_node_incident(monkeypatch, shutdown_only):
    """Seeded leak on a 2-node cluster -> exactly one `object_leak`
    incident with job/callsite attribution (cooldown respected)."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import state

    monkeypatch.setenv("RTPU_memory_leak_sweep_period_s", "0.4")
    monkeypatch.setenv("RTPU_memory_leak_min_age_s", "0")
    monkeypatch.setenv("RTPU_memory_leak_cooldown_s", "300")
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"resources": {"CPU": 2, "n1": 1}},
    )
    cluster.add_node(resources={"CPU": 2, "n2": 1}, node_name="n2")
    try:
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)
        core = worker_mod.global_worker
        job_hex = core.job_id.hex()

        @ray_tpu.remote(resources={"n2": 1})
        def leaky():
            return np.zeros(300_000, dtype=np.uint8)

        ref = leaky.remote()
        ray_tpu.get(ref)  # materialized: pinned on node 2, owner = driver
        oid = ref.object_id()
        # seed the leak: drop the owner's ledger entry WITHOUT running the
        # free path — exactly what a lost FreeObjects / refcount bug does
        with core.refs._lock:
            assert core.refs._owned.pop(oid, None) is not None

        deadline = time.time() + 30
        incident = None
        while time.time() < deadline:
            incs = [i for i in state.list_incidents(
                cluster.address, detail=True)
                if i.get("kind") == "object_leak"]
            if incs:
                incident = incs[-1]
                break
            time.sleep(0.3)
        assert incident is not None, "no object_leak incident raised"
        leaks = incident.get("leaks") or []
        assert any(l["object_id"] == oid.hex() for l in leaks), leaks
        rec = next(l for l in leaks if l["object_id"] == oid.hex())
        assert rec["job_id"] == job_hex[: len(rec["job_id"])]
        assert rec["callsite"].startswith("task:")
        assert rec["callsite"].endswith("leaky")
        assert rec["size"] >= 300_000
        # attribution names the node that holds the primary (node 2)
        n2 = [n for n in state.list_nodes(cluster.address)
              if n["resources_total"].get("n2")]
        assert rec["node_id"] == n2[0]["node_id"]
        # exactly once: more sweeps must not re-open the same leak
        time.sleep(1.5)
        count = len([i for i in state.list_incidents(cluster.address)
                     if i.get("kind") == "object_leak"])
        assert count == 1, f"leak incident fired {count} times"
        # the leak also surfaces on the state/CLI path with attribution
        found = state.find_memory_leaks(cluster.address, sweep=False)
        assert any(l["object_id"] == oid.hex() for l in found)
        # and in the prometheus gauge's source data
        report = state.memory_report(cluster.address,
                                     include_objects=False)
        leaked_total = sum(l.get("size") or 0
                           for n in report["nodes"] for l in n["leaks"])
        assert leaked_total >= 300_000
    finally:
        import ray_tpu as _rt

        if _rt.is_initialized():
            _rt.shutdown()
        cluster.shutdown()


# ------------------------------------------------- cluster: OOM forensics


def test_sigkilled_worker_death_report_carries_memory_snapshot(
        monkeypatch, shutdown_only):
    """The periodic on-disk ledger snapshot reaches a SIGKILLed actor's
    ActorDiedError — the OOM-forensics path (the memory monitor rides the
    same attach, plus a live grab, when it does the killing)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.exceptions import ActorDiedError
    from ray_tpu.util import state

    monkeypatch.setenv("RTPU_memory_snapshot_period_s", "0.5")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class Hoarder:
        def __init__(self):
            self.refs = []

        def hoard(self):
            self.refs.append(
                ray_tpu.put(np.zeros(400_000, dtype=np.uint8)))
            return os.getpid()

    a = Hoarder.remote()
    pid = ray_tpu.get(a.hoard.remote())
    ray_tpu.get(a.hoard.remote())
    time.sleep(2.5)  # let the snapshot cadence persist the ledger
    os.kill(pid, signal.SIGKILL)
    deadline = time.time() + 40
    msg = ""
    while time.time() < deadline:
        try:
            ray_tpu.get(a.hoard.remote(), timeout=5)
        except ActorDiedError as e:
            msg = str(e)
            if "memory snapshot" in msg:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert "memory snapshot at death" in msg, f"no snapshot in: {msg!r}"
    assert "rss=" in msg
    assert "plasma" in msg  # the hoarded plasma objects are the top holders
    assert "test_memory_plane.py:" in msg  # with their creation callsites
    # the same text is on the state API's death record
    dead = state.list_actors(filters=[("state", "=", "DEAD")])
    assert any("memory snapshot at death" in (d.get("death_cause") or "")
               for d in dead)
    ray_tpu.shutdown()
