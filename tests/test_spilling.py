"""Object spilling + OOM monitor.

Reference contracts: pinned primary copies spill to disk under store
pressure and restore on access (src/ray/raylet/local_object_manager.h:41);
the raylet kills workers when node memory crosses a threshold and the task
fails with OutOfMemoryError when retries are exhausted
(src/ray/common/memory_monitor.h:52, worker_killing_policy*.h).
"""

import numpy as np
import pytest


def test_put_2x_capacity_spills_and_restores(shutdown_only):
    """A workload 2x plasma capacity completes via spill-to-disk."""
    import ray_tpu

    capacity = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=capacity)

    rng = np.random.default_rng(0)
    n, size = 16, 8 * 1024 * 1024  # 128 MiB of primaries in a 64 MiB store
    arrays = [rng.integers(0, 255, size=size, dtype=np.uint8) for _ in range(n)]
    refs = [ray_tpu.put(a) for a in arrays]

    # Every object must come back intact, in arbitrary access order.
    order = rng.permutation(n)
    for i in order:
        out = ray_tpu.get(refs[i], timeout=120)
        assert np.array_equal(out, arrays[i]), f"object {i} corrupted"


def test_task_returns_spill(shutdown_only):
    """Large task returns exceed capacity and still all materialize."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)

    @ray_tpu.remote
    def make(i):
        r = np.random.default_rng(i)
        return r.integers(0, 255, size=8 * 1024 * 1024, dtype=np.uint8)

    refs = [make.remote(i) for i in range(16)]
    # Fetch one at a time: results are zero-copy views over plasma, so
    # holding all 2x-capacity results at once cannot fit by construction
    # (same store-capacity contract as the reference).
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=170)
        expect = np.random.default_rng(i).integers(
            0, 255, size=8 * 1024 * 1024, dtype=np.uint8
        )
        assert np.array_equal(out, expect)
        del out


def test_oom_monitor_kills_worker(shutdown_only, monkeypatch):
    """threshold=0 makes every leased worker an OOM victim: the task dies
    with OutOfMemoryError naming the memory monitor, instead of hanging."""
    import ray_tpu
    from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError

    monkeypatch.setenv("RTPU_memory_usage_threshold", "0.0")
    monkeypatch.setenv("RTPU_memory_monitor_refresh_ms", "100")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote(max_retries=0)
    def hog():
        import time

        time.sleep(30)
        return 1

    with pytest.raises((OutOfMemoryError, WorkerCrashedError)) as exc_info:
        ray_tpu.get(hog.remote(), timeout=60)
    # The death reason should be attributed to the memory monitor.
    assert "memory monitor" in str(exc_info.value)


def test_oom_victim_policy():
    """Task workers die before actor workers; newest first within a class."""
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.raylet.main import NodeManager

    class H:
        def __init__(self, wid, token, alive=True, leased=True, pid=1):
            self.worker_id = wid
            self.startup_token = token
            self.alive = alive
            self.leased = leased
            self.pid = pid

    nm = object.__new__(NodeManager)  # policy only; no cluster needed
    nm._actor_workers = {b"actor1": b"aid"}

    class Pool:
        workers = {
            b"task_old": H(b"task_old", 1),
            b"task_new": H(b"task_new", 5),
            b"actor1": H(b"actor1", 9),
            b"idle": H(b"idle", 7, leased=False),
        }

    nm.worker_pool = Pool()
    victim = nm._pick_oom_victim()
    assert victim.worker_id == b"task_new"  # newest task worker
    Pool.workers.pop(b"task_new")
    Pool.workers.pop(b"task_old")
    assert nm._pick_oom_victim().worker_id == b"actor1"  # actors last
    Pool.workers.pop(b"actor1")
    assert nm._pick_oom_victim() is None  # idle workers are not victims
