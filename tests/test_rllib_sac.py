"""SAC + multi-agent learning tests (reference: rllib learning tests —
threshold-based; SAC is the off-policy/continuous-control pillar,
sac.py:407; the multi-agent runner is multi_agent_env_runner.py:55)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rl_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_numpy_gaussian_matches_flax():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core.rl_module import (
        SquashedGaussianModule,
        numpy_gaussian_forward,
    )

    mod = SquashedGaussianModule(action_dim=2, hidden=(16, 16))
    params = mod.init_params(obs_dim=3, seed=0)
    obs = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    mean_j, logstd_j = mod.apply({"params": params}, jnp.asarray(obs))
    mean_n, logstd_n = numpy_gaussian_forward(
        jax.tree.map(np.asarray, params), obs
    )
    np.testing.assert_allclose(mean_n, np.asarray(mean_j), atol=1e-5)
    np.testing.assert_allclose(logstd_n, np.asarray(logstd_j), atol=1e-5)


def test_sac_update_shapes():
    from ray_tpu.rllib.algorithms.sac import SACLearner

    learner = SACLearner(3, 1, [-2.0], [2.0], hidden=(32, 32), seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(64, 3)).astype(np.float32),
        "next_obs": rng.normal(size=(64, 3)).astype(np.float32),
        "actions": rng.uniform(-2, 2, size=(64, 1)).astype(np.float32),
        "rewards": rng.normal(size=64).astype(np.float32),
        "dones": np.zeros(64, np.float32),
    }
    aux = learner.update(batch)
    for key in ("critic_loss", "actor_loss", "alpha_loss", "alpha",
                "entropy"):
        assert np.isfinite(aux[key]), aux


def test_sac_learns_pendulum(rl_cluster):
    """SAC reaches clearly-better-than-random on Pendulum-v1 (random policy
    averages about -1200; the threshold proves the twin-critic +
    temperature machinery optimizes)."""
    from ray_tpu.rllib import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=8,
                     rollout_fragment_length=16)
        .training(model_hidden=(64, 64), learning_starts=1_000,
                  train_batch_size=128, learner_steps_per_iteration=64)
        .debugging(seed=0)
        .build()
    )
    try:
        best = -1e9
        for _ in range(350):
            result = algo.train()
            # only trust the mean once enough episodes fill the window —
            # a near-empty deque of lucky random episodes can spike early
            if result["num_env_steps_sampled_lifetime"] >= 12_000:
                best = max(best, result["episode_return_mean"])
                if best > -450:
                    break
        assert best > -450, f"SAC failed to learn Pendulum: best {best}"
    finally:
        algo.stop()


def test_multi_agent_env_runner_batches(rl_cluster):
    from ray_tpu.rllib.core.rl_module import ActorCriticModule
    from ray_tpu.rllib.env.multi_agent import (
        MultiAgentCartPole,
        MultiAgentEnvRunner,
    )

    runner = MultiAgentEnvRunner(
        lambda: MultiAgentCartPole(num_agents=2),
        lambda aid: aid,  # one policy per agent
        gamma=0.99, lambda_=0.95, seed=0,
    )
    spaces = runner.spaces()
    assert set(spaces) == {"agent_0", "agent_1"}
    assert spaces["agent_0"] == (4, 2)
    params = {
        pid: ActorCriticModule(num_actions=2, hidden=(16,)).init_params(4)
        for pid in spaces
    }
    batches = runner.sample(params, rollout_len=100)
    for pid, batch in batches.items():
        n = len(batch["obs"])
        assert n > 0
        for key in ("actions", "logp_old", "advantages", "returns"):
            assert len(batch[key]) == n, (pid, key)
        assert np.isfinite(batch["advantages"]).all()


def test_multi_agent_ppo_learns(rl_cluster):
    """2-agent MultiAgentCartPole with a policy PER AGENT: the joint
    return (sum over both agents) must clear 2x the single-agent
    threshold — both policies have to learn."""
    from ray_tpu.rllib import MultiAgentPPO, MultiAgentPPOConfig
    from ray_tpu.rllib.env.multi_agent import MultiAgentCartPole

    algo = (
        MultiAgentPPOConfig()
        .environment(lambda: MultiAgentCartPole(num_agents=2))
        .multi_agent(policy_mapping_fn=lambda aid: aid)
        .env_runners(num_env_runners=2, rollout_fragment_length=256)
        .training(lr=3e-4, num_epochs=6, minibatch_size=128,
                  model_hidden=(64, 64))
        .debugging(seed=0)
        .build()
    )
    assert isinstance(algo, MultiAgentPPO)
    try:
        best = 0.0
        for _ in range(80):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best > 110:
                break
        # random play totals ~40 (2 x ~20); 110 needs both agents improving
        # (the joint return is the sum over both policies' episodes)
        assert best > 110, f"multi-agent PPO failed to learn: best {best}"
    finally:
        algo.stop()
