"""TorchTrainer tests (reference: python/ray/train/tests/test_torch_trainer.py
— DDP over gloo on CPU workers, gradient sync + session machinery)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import Checkpoint, RunConfig, ScalingConfig
from ray_tpu.train.torch import TorchConfig, TorchTrainer


@pytest.fixture(scope="module")
def torch_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_torch_ddp_trains_and_syncs(torch_cluster, tmp_path):
    """2-rank DDP regression fit: loss must descend and both ranks must end
    with identical weights (the DDP allreduce contract)."""

    def loop(config):
        import torch
        import torch.distributed as dist
        import torch.nn as nn

        from ray_tpu import train
        from ray_tpu.train.torch import prepare_model

        ctx = train.get_context()
        assert dist.is_initialized()
        assert dist.get_world_size() == 2
        assert dist.get_rank() == ctx.get_world_rank()

        torch.manual_seed(1234 + ctx.get_world_rank())
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        g = torch.Generator().manual_seed(ctx.get_world_rank())
        x = torch.randn(64, 4, generator=g)
        y = x @ torch.tensor([[1.0], [2.0], [-1.0], [0.5]]) + 0.1

        first = last = None
        for step in range(30):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            if first is None:
                first = float(loss)
            last = float(loss)
            if step % 10 == 9:
                train.report({"loss": last, "rank": ctx.get_world_rank()})
        w = model.module.weight.detach().numpy().copy()
        train.report({"loss": last, "final_w": w.tolist(), "first": first})

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="torch_ddp", storage_path=str(tmp_path)),
        torch_config=TorchConfig(backend="gloo"),
    )
    result = trainer.fit()
    assert result.metrics["loss"] < result.metrics["first"] * 0.2
    # rank-0 metrics win; weights after DDP must match across ranks —
    # verified implicitly: DDP broadcasts rank-0 params at wrap time and
    # allreduces grads, so a descending shared loss proves sync. Check the
    # final weight is close to the generating matrix.
    w = np.asarray(result.metrics["final_w"]).ravel()
    np.testing.assert_allclose(w, [1.0, 2.0, -1.0, 0.5], atol=0.25)


def test_prepare_data_loader_shards(torch_cluster, tmp_path):
    def loop(config):
        import torch.utils.data as tud

        from ray_tpu import train
        from ray_tpu.train.torch import prepare_data_loader

        ds = list(range(100))
        loader = tud.DataLoader(ds, batch_size=10)
        sharded = prepare_data_loader(loader)
        seen = [int(x) for batch in sharded for x in batch]
        train.report({"n": len(seen),
                      "rank": train.get_context().get_world_rank()})

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="torch_shard", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["n"] == 50  # half of the dataset per rank


def test_single_worker_no_dist(torch_cluster, tmp_path):
    def loop(config):
        import torch.distributed as dist
        import torch.nn as nn

        from ray_tpu import train
        from ray_tpu.train.torch import prepare_model

        assert not dist.is_initialized()
        m = prepare_model(nn.Linear(2, 1))
        assert isinstance(m, nn.Linear)  # no DDP wrap for world_size 1
        train.report({"ok": 1})

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="torch_single", storage_path=str(tmp_path)),
    )
    assert trainer.fit().metrics["ok"] == 1
