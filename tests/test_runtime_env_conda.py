"""runtime_env conda + container: workers under a different interpreter or
inside a container (reference: _private/runtime_env/conda.py:260,
image_uri.py:96). No conda binary or docker daemon ships in this image, so
both tests install executable fakes on PATH — like the reference's mocked
container/conda plumbing, but driven through a REAL subprocess exec: the
raylet genuinely builds the env / composes the docker argv, the worker
genuinely spawns through it, and a real task runs inside."""

import json
import os
import stat
import sys
import time

import pytest

import ray_tpu


@pytest.fixture
def fake_conda(tmp_path, monkeypatch):
    """`conda` shim: `env create -p DIR -f YML` materializes DIR/bin/python
    as a symlink to this interpreter (same ABI — exactly what a real conda
    env with a matching python version provides), recording the call."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    log = tmp_path / "conda.log"
    conda = bindir / "conda"
    conda.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
with open({str(log)!r}, "a") as f:
    f.write(json.dumps(args) + "\\n")
if args[:2] == ["env", "create"]:
    prefix = args[args.index("-p") + 1]
    os.makedirs(os.path.join(prefix, "bin"), exist_ok=True)
    # a wrapper (not a bare symlink): a symlink without pyvenv.cfg would
    # make CPython treat the fake env dir as sys.prefix and lose the base
    # env's site-packages; real conda envs ship their own interpreter+libs
    py = os.path.join(prefix, "bin", "python")
    with open(py, "w") as f:
        f.write("#!/bin/sh\\nexec {sys.executable} \\"$@\\"\\n")
    os.chmod(py, 0o755)
    # the env advertises itself so tasks can prove where they ran
    open(os.path.join(prefix, ".built-by-fake-conda"), "w").write("1")
elif args[:2] == ["env", "list"]:
    print(json.dumps({{"envs": []}}))
sys.exit(0)
""")
    conda.chmod(conda.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RTPU_CONDA_EXE", str(conda))
    return log


@pytest.fixture
def fake_docker(tmp_path, monkeypatch):
    """`docker` shim: `docker run [opts] image cmd...` records the argv and
    execs cmd locally — the container boundary is faked, the worker spawn,
    registration and task execution are real."""
    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    log = tmp_path / "docker.log"
    docker = bindir / "docker"
    docker.write_text(f"""#!{sys.executable}
import json, os, sys
args = sys.argv[1:]
assert args[0] == "run", args
i = 1
seen = []
while i < len(args):
    a = args[i]
    if a in ("-v", "-e", "--name"):
        seen.append(args[i + 1]); i += 2
    elif a.startswith("-"):
        seen.append(a); i += 1
    else:
        break  # the image name
image, cmd = args[i], args[i + 1:]
with open({str(log)!r}, "a") as f:
    f.write(json.dumps({{"image": image, "opts": seen, "cmd": cmd[:3]}}) + "\\n")
os.environ["RTPU_FAKE_CONTAINER_IMAGE"] = image
os.execvp(cmd[0], cmd)
""")
    docker.chmod(docker.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RTPU_CONTAINER_EXE", str(docker))
    return log


@pytest.fixture
def env_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_conda_env_builds_caches_and_hosts_tasks(fake_conda, env_cluster):
    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["pip"]}})
    def where():
        import sys

        prefix = os.environ.get("CONDA_PREFIX", "")
        return {
            "conda_prefix": prefix,
            "built_marker": os.path.exists(
                os.path.join(prefix, ".built-by-fake-conda")),
            "exe_is_env_python": "conda-" in os.path.realpath(sys.argv[0])
            or True,  # symlinked interpreter resolves to the base python
            "pid": os.getpid(),
        }

    r1 = ray_tpu.get(where.remote(), timeout=120)
    assert r1["conda_prefix"] and r1["built_marker"]
    assert "conda-" in r1["conda_prefix"]  # hash-keyed env dir
    # second task with the SAME spec: env is cached (one create call) and
    # the worker can be reused
    r2 = ray_tpu.get(where.remote(), timeout=120)
    assert r2["conda_prefix"] == r1["conda_prefix"]
    creates = [json.loads(l) for l in
               fake_conda.read_text().splitlines()
               if json.loads(l)[:2] == ["env", "create"]]
    assert len(creates) == 1, creates
    argv = creates[0]
    assert "-p" in argv and "-f" in argv and "--yes" in argv


def test_conda_prefix_string_and_isolation(fake_conda, tmp_path,
                                           env_cluster):
    # build a "prebuilt" env via the fake, then reference it by prefix path
    import subprocess

    prefix = str(tmp_path / "preenv")
    subprocess.run([os.environ["RTPU_CONDA_EXE"], "env", "create", "--yes",
                    "-p", prefix, "-f", "/dev/null"], check=True)

    @ray_tpu.remote(runtime_env={"conda": prefix})
    def in_env():
        return os.environ.get("CONDA_PREFIX")

    @ray_tpu.remote
    def base_env():
        return os.environ.get("CONDA_PREFIX", "")

    assert ray_tpu.get(in_env.remote(), timeout=120) == prefix
    # plain tasks keep the base interpreter (no env leak across pools)
    assert ray_tpu.get(base_env.remote(), timeout=60) != prefix


def test_container_runtime_env(fake_docker, env_cluster):
    @ray_tpu.remote(
        runtime_env={"container": {"image": "rayproject/tpu:latest",
                                   "run_options": ["-e", "XYZ=1"]}})
    def inside():
        return {
            "image": os.environ.get("RTPU_FAKE_CONTAINER_IMAGE", ""),
            "pid": os.getpid(),
        }

    r = ray_tpu.get(inside.remote(), timeout=120)
    assert r["image"] == "rayproject/tpu:latest"
    rec = json.loads(fake_docker.read_text().splitlines()[0])
    assert rec["image"] == "rayproject/tpu:latest"
    assert "--rm" in rec["opts"] and "--network=host" in rec["opts"]
    assert "/dev/shm:/dev/shm" in rec["opts"]
    assert rec["cmd"][1:3] == ["-m", "ray_tpu._private.workers.default_worker"]


def test_container_worker_death_detected(fake_docker, env_cluster):
    @ray_tpu.remote(
        runtime_env={"container": {"image": "img:1"}})
    class A:
        def pid(self):
            return os.getpid()

        def boom(self):
            os._exit(9)

    a = A.remote()
    pid = ray_tpu.get(a.pid.remote(), timeout=120)
    assert pid > 0
    a.boom.remote()
    from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError

    with pytest.raises((ActorDiedError, WorkerCrashedError)):
        for _ in range(40):
            ray_tpu.get(a.pid.remote(), timeout=30)
            time.sleep(0.5)
