"""Slice-granular autoscaler: demand scheduler + fake-provider e2e.

Reference contracts: v2 ResourceDemandScheduler picks node types for
unplaceable demand (autoscaler/v2/scheduler.py:624), the autoscaler reads
cluster load from the GCS (gcs_autoscaler_state_manager.h:30), and the fake
multi-node provider enables cloud-free e2e
(autoscaler/_private/fake_multi_node/node_provider.py). TPU twist: node
types are whole slices; a pending TPU-<type>-head demand launches a slice.
"""

import time

import pytest

V5E8 = {"CPU": 8.0, "TPU": 8.0, "TPU-V5E-8-head": 1.0}


def test_scheduler_picks_slice_for_head_demand():
    from ray_tpu.autoscaler.scheduler import ResourceDemandScheduler

    sched = ResourceDemandScheduler(
        {
            "cpu-small": {"resources": {"CPU": 4.0}, "max_workers": 10},
            "tpu-v5e-8": {"resources": dict(V5E8), "max_workers": 4},
        }
    )
    # Slice-head demand can only fit the slice type.
    to_launch, infeasible = sched.schedule(
        [{"TPU-V5E-8-head": 1.0, "TPU": 8.0}], [], {}
    )
    assert to_launch == {"tpu-v5e-8": 1} and not infeasible

    # A CPU demand prefers the smallest satisfying type.
    to_launch, _ = sched.schedule([{"CPU": 2.0}], [], {})
    assert to_launch == {"cpu-small": 1}

    # Demand that fits existing capacity launches nothing.
    to_launch, _ = sched.schedule([{"CPU": 2.0}], [{"CPU": 4.0}], {})
    assert to_launch == {}

    # Two slice demands -> two slices; max_workers caps the third.
    to_launch, infeasible = sched.schedule(
        [{"TPU-V5E-8-head": 1.0}] * 3, [], {"tpu-v5e-8": 2}
    )
    assert to_launch == {"tpu-v5e-8": 2}
    assert len(infeasible) == 1

    # Bin-packing: 4 x CPU:2 demands pack into one cpu-small plus one more.
    to_launch, _ = sched.schedule([{"CPU": 2.0}] * 4, [], {})
    assert to_launch == {"cpu-small": 2}


def test_scheduler_min_workers():
    from ray_tpu.autoscaler.scheduler import ResourceDemandScheduler

    sched = ResourceDemandScheduler(
        {"tpu-v5e-8": {"resources": dict(V5E8), "min_workers": 2, "max_workers": 4}}
    )
    assert sched.min_workers_to_launch({}) == {"tpu-v5e-8": 2}
    assert sched.min_workers_to_launch({"tpu-v5e-8": 3}) == {}


def test_autoscaler_update_with_recording_provider(shutdown_only):
    """Pending actor demand visible in GCS load triggers a launch decision."""
    import ray_tpu
    from ray_tpu import api
    from ray_tpu.autoscaler import Autoscaler, NodeTypeConfig
    from ray_tpu.autoscaler.node_provider import RecordingNodeProvider

    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote(resources={"SLICE": 1.0})
    class OnSlice:
        def where(self):
            return "slice"

    actor = OnSlice.remote()  # unplaceable until a slice node exists
    provider = RecordingNodeProvider()
    scaler = Autoscaler(
        gcs_address=api._local_node.gcs_address,
        provider=provider,
        node_types={
            "fake-slice": NodeTypeConfig(
                resources={"CPU": 4.0, "SLICE": 1.0}, max_workers=2
            )
        },
    )
    deadline = time.time() + 30
    while time.time() < deadline and not provider.launches:
        scaler.update()
        time.sleep(0.5)
    assert provider.launches == ["fake-slice"]
    # The demand is now covered by the pending node; no duplicate launch.
    scaler.update()
    assert provider.launches == ["fake-slice"]
    del actor


def test_autoscaler_e2e_fake_provider(shutdown_only):
    """Slice-head demand -> fake provider launches a REAL raylet -> the
    pending actor schedules onto it and answers."""
    import ray_tpu
    from ray_tpu import api
    from ray_tpu.autoscaler import Autoscaler, FakeMultiNodeProvider, NodeTypeConfig

    ray_tpu.init(num_cpus=2)
    gcs_address = api._local_node.gcs_address
    session_dir = api._local_node.session_dir

    node_types = {
        "fake-v5e-8": NodeTypeConfig(
            resources={"CPU": 4.0, "TPU": 8.0, "TPU-V5E-8-head": 1.0},
            max_workers=2,
        )
    }
    provider = FakeMultiNodeProvider(
        gcs_address,
        {k: v.to_dict() for k, v in node_types.items()},
        session_dir=session_dir,
    )
    scaler = Autoscaler(
        gcs_address, provider, node_types, update_interval_s=0.5
    )
    scaler.start()
    try:

        @ray_tpu.remote(resources={"TPU-V5E-8-head": 1.0})
        class SliceWorker:
            def hello(self):
                return "from-the-slice"

        w = SliceWorker.remote()
        # The actor is unplaceable on the head; the autoscaler must notice
        # and launch the fake slice node, then the GCS schedules onto it.
        assert ray_tpu.get(w.hello.remote(), timeout=90) == "from-the-slice"
        assert len(provider.non_terminated_nodes()) == 1
    finally:
        scaler.stop()
        import ray_tpu as _rt

        _rt.shutdown()
        provider.shutdown()


def test_command_node_provider_launches_real_node(tmp_path):
    """CommandNodeProvider runs user shell commands to provision nodes:
    the 'up' command here is the real operator CLI, and the launched node
    joins the cluster (reference: the local/on-prem provider story)."""
    import subprocess
    import sys
    import time

    import ray_tpu
    from ray_tpu import api
    from ray_tpu.autoscaler.node_provider import CommandNodeProvider

    ray_tpu.init(num_cpus=2)
    try:
        gcs = api._local_node.gcs_address
        import uuid

        token = f"prov_{uuid.uuid4().hex[:8]}"
        up = (
            f"{sys.executable} -m ray_tpu.scripts start "
            "--address $gcs_address "
            f"--resources '{{\"CPU\": 1, \"{token}\": 1}}'"
        )
        provider = CommandNodeProvider(gcs, {"worker": {"up": up}})
        (pid,) = provider.create_node("worker")
        assert provider.non_terminated_nodes() == {pid: "worker"}

        deadline = time.time() + 60
        while True:
            alive = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(alive) == 2:
                break
            assert time.time() < deadline, alive
            time.sleep(0.5)

        @ray_tpu.remote(resources={token: 1})
        def where():
            return ray_tpu.get_runtime_context().get_node_id()

        assert ray_tpu.get(where.remote(), timeout=60)
        provider.terminate_node(pid)  # no down command: bookkeeping only
        assert provider.non_terminated_nodes() == {}
    finally:
        ray_tpu.shutdown()
        # reap the CLI-launched raylet (no down command in this test)
        # the unique resource token appears only in THIS node's argv
        subprocess.run(["pkill", "-f", token], capture_output=True)


def test_command_node_provider_command_contract(tmp_path):
    """Placeholders format into commands; failures surface loudly; down
    runs on terminate."""
    from ray_tpu.autoscaler.node_provider import CommandNodeProvider

    up_marker = tmp_path / "up.log"
    down_marker = tmp_path / "down.log"
    provider = CommandNodeProvider("1.2.3.4:5", {
        "t": {
            "up": f"echo $provider_node_id $gcs_address >> {up_marker}",
            "down": f"echo $provider_node_id >> {down_marker}",
        },
        "bad": {"up": "exit 3"},
    })
    (pid,) = provider.create_node("t")
    assert up_marker.read_text().strip() == f"{pid} 1.2.3.4:5"
    provider.terminate_node(pid)
    assert down_marker.read_text().strip() == pid
    assert provider.non_terminated_nodes() == {}

    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="provider command failed"):
        provider.create_node("bad")
