"""Llama model family tests on the virtual 8-device CPU mesh.

Same semantics-preservation contract as test_train_step.py: every parallelism
axis combination must give the single-device loss trajectory, because the
shardings only move FLOPs. Plus unit checks for RoPE and GQA math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import (
    LlamaConfig,
    apply_rope,
    forward,
    init_params,
    num_params,
    rope_angles,
)
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.parallel.train_step import TrainStep

CFG = LlamaConfig.tiny(use_flash_attention=False, dtype=jnp.float32)


def _batch(rng, B=8, T=64):
    idx = rng.integers(0, CFG.vocab_size, size=(B, T)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1)
    return {"idx": jnp.asarray(idx), "targets": jnp.asarray(tgt)}


def _run(mesh, steps=4):
    ts = TrainStep(CFG, mesh, learning_rate=5e-3)
    state = ts.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(steps):
        batch = ts.shard_batch(_batch(rng))
        state, m = ts.step(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def baseline():
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    return _run(mesh)


@pytest.mark.parametrize(
    "axes",
    [
        {"dp": 8},
        {"fsdp": 8},
        {"tp": 4, "dp": 2},
        {"sp": 4, "dp": 2},
        {"dp": 2, "fsdp": 2, "tp": 2},
    ],
)
def test_parallel_matches_single_device(axes, baseline):
    base_losses, _ = baseline
    losses, _ = _run(make_mesh(axes))
    np.testing.assert_allclose(losses, base_losses, rtol=2e-3, atol=2e-3)
    assert losses[-1] < losses[0]


def test_rope_rotation_properties():
    # rotating by position p then querying against position p+k depends only
    # on k (relative-position property of RoPE)
    D = 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 4, 1, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 4, 1, D)), jnp.float32)
    ang0 = rope_angles(D, 10000.0, jnp.arange(4))
    ang5 = rope_angles(D, 10000.0, jnp.arange(4) + 5)
    dots0 = jnp.einsum("bthd,bshd->ts", apply_rope(q, ang0), apply_rope(k, ang0))
    dots5 = jnp.einsum("bthd,bshd->ts", apply_rope(q, ang5), apply_rope(k, ang5))
    np.testing.assert_allclose(dots0, dots5, rtol=1e-4, atol=1e-4)
    # norm preservation
    np.testing.assert_allclose(
        jnp.linalg.norm(apply_rope(q, ang0)), jnp.linalg.norm(q), rtol=1e-5
    )


def test_pos_offset_matches_full_sequence():
    # forward of the second half with pos_offset equals the second half of the
    # full forward when attention is bidirectionally blocked... for a causal
    # model the first half context differs, so check the embedding-free path:
    # RoPE angles themselves.
    D = 8
    full = rope_angles(D, 1e4, jnp.arange(16))
    shifted = rope_angles(D, 1e4, jnp.arange(8) + 8)
    np.testing.assert_allclose(full[8:], shifted, rtol=0, atol=0)


def test_gqa_matches_mha_when_kv_repeated():
    # a GQA model with n_kv_head == n_head is plain MHA; with fewer kv heads
    # the output must still be finite and the param count smaller
    cfg_mha = LlamaConfig.tiny(n_kv_head=4, use_flash_attention=False,
                               dtype=jnp.float32)
    cfg_gqa = LlamaConfig.tiny(n_kv_head=2, use_flash_attention=False,
                               dtype=jnp.float32)
    p_mha = init_params(cfg_mha)
    p_gqa = init_params(cfg_gqa)
    assert num_params(p_gqa) < num_params(p_mha)
    idx = jnp.zeros((2, 16), jnp.int32)
    out = forward(cfg_gqa, p_gqa, idx)
    assert out.shape == (2, 16, cfg_gqa.vocab_size)
    assert bool(jnp.isfinite(out).all())


def test_state_is_sharded():
    mesh = make_mesh({"fsdp": 4, "tp": 2})
    ts = TrainStep(CFG, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    kernel = state["params"]["h_0"]["attn"]["wq"]["kernel"]
    assert len(kernel.sharding.device_set) == 8
    mu = state["opt_state"][1][0].mu["h_0"]["attn"]["wq"]["kernel"]
    assert mu.sharding == kernel.sharding
