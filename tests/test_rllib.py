"""RLlib slice tests (reference: rllib learning tests — threshold-based)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig


@pytest.fixture(scope="module")
def rl_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_numpy_forward_matches_flax():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core.rl_module import ActorCriticModule, numpy_forward

    mod = ActorCriticModule(num_actions=3, hidden=(16, 16))
    params = mod.init_params(obs_dim=4, seed=0)
    obs = np.random.default_rng(0).normal(size=(7, 4)).astype(np.float32)
    logits_j, v_j = mod.apply({"params": params}, jnp.asarray(obs))
    logits_n, v_n = numpy_forward(jax.tree.map(np.asarray, params), obs)
    np.testing.assert_allclose(logits_n, np.asarray(logits_j), atol=1e-5)
    np.testing.assert_allclose(v_n, np.asarray(v_j), atol=1e-5)


def test_env_runner_batch_shapes(rl_cluster):
    from ray_tpu.rllib.env.env_runner import EnvRunnerGroup

    group = EnvRunnerGroup("CartPole-v1", num_runners=2,
                           num_envs_per_runner=4, gamma=0.99, lambda_=0.95)
    obs_dim, num_actions = group.obs_and_action_dims()
    assert (obs_dim, num_actions) == (4, 2)
    from ray_tpu.rllib.core.rl_module import ActorCriticModule

    params = ActorCriticModule(num_actions=2).init_params(obs_dim)
    import jax

    batch = group.sample(jax.tree.map(np.asarray, params), rollout_len=32)
    n = 2 * 4 * 32
    # autoreset rows (one fabricated transition per episode end) are
    # dropped, so the batch is slightly smaller than T*N
    got = batch["obs"].shape[0]
    assert 0.8 * n <= got <= n, (got, n)
    assert batch["obs"].shape[1] == 4
    assert batch["actions"].shape == (got,)
    assert batch["advantages"].shape == (got,)
    assert np.isfinite(batch["advantages"]).all()
    group.shutdown()


def test_ppo_cartpole_learns(rl_cluster):
    """The learning test (reference: rllib tuned_examples threshold runs):
    CartPole mean return must reach 150 within 60 iterations, with rollouts
    on CPU actors and the learner's pjit update on the 8-device mesh inside
    a learner actor."""
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=4, num_envs_per_env_runner=8,
                     rollout_fragment_length=64)
        .training(lr=1e-3, num_epochs=8, minibatch_size=256,
                  entropy_coeff=0.005)
        .debugging(seed=0)
        .build()
    )
    try:
        assert algo.learner_group.num_devices() == 8, "mesh must span 8 devices"
        best = 0.0
        for i in range(120):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 150:
                break
        assert best >= 150, f"PPO failed to learn CartPole: best={best:.1f}"
    finally:
        algo.stop()


def test_ppo_save_restore(rl_cluster, tmp_path):
    import jax
    import numpy as np

    from ray_tpu.rllib import PPO

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .build()
    )
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ck"))
        w0 = algo.get_weights()
    finally:
        algo.stop()

    algo2 = PPO.from_checkpoint(path)
    try:
        for a, b in zip(jax.tree.leaves(w0),
                        jax.tree.leaves(algo2.get_weights())):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        algo2.train()
    finally:
        algo2.stop()
