"""Ray Serve subset: deployments, replicas, pow-2 routing, HTTP proxy,
composition, recovery, batching, autoscaling.

Reference contracts: serve.run deploys via the controller actor
(serve/_private/controller.py:86), requests flow handle -> router ->
pow-2 scheduler -> replica (handle.py:714, pow_2_scheduler.py:49,
replica.py:231), HTTP ingress routes by prefix (proxy.py:1130).
"""

import json
import os
import time
import urllib.request

import pytest


@pytest.fixture
def serve_cluster():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8)
    yield serve
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    def square(x):
        return x * x

    handle = serve.run(square.bind(), name="sq", route_prefix="/sq")
    assert handle.remote(7).result(timeout=30) == 49


def test_class_deployment_two_replicas(serve_cluster):
    serve = serve_cluster

    @serve.deployment(num_replicas=2)
    class Worker:
        def __call__(self, x):
            return (os.getpid(), x + 1)

    handle = serve.run(Worker.bind(), name="w", route_prefix="/w")
    pids = set()
    for i in range(30):
        pid, val = handle.remote(i).result(timeout=30)
        assert val == i + 1
        pids.add(pid)
    assert len(pids) == 2  # pow-2 routing spreads across both replicas


def test_composition(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    class Preprocessor:
        def __call__(self, x):
            return x * 10

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            stage1 = self.pre.remote(x).result(timeout=30)
            return stage1 + 1

    handle = serve.run(Model.bind(Preprocessor.bind()), name="comp",
                       route_prefix="/comp")
    assert handle.remote(4).result(timeout=30) == 41


def test_http_proxy(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    def echo(payload=None):
        if payload is None:
            return {"hello": "world"}
        return {"got": payload}

    serve.run(echo.bind(), name="echo", route_prefix="/echo")
    port = serve.start()

    with urllib.request.urlopen(f"http://127.0.0.1:{port}/echo", timeout=30) as r:
        assert json.loads(r.read()) == {"hello": "world"}

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/echo",
        data=json.dumps({"x": 1}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read()) == {"got": {"x": 1}}

    # Unknown route -> 404.
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_replica_recovery(serve_cluster):
    import ray_tpu

    serve = serve_cluster

    @serve.deployment
    class Fragile:
        def __call__(self):
            return os.getpid()

        def die(self):
            os._exit(1)

    handle = serve.run(Fragile.bind(), name="fragile", route_prefix="/fragile")
    pid1 = handle.remote().result(timeout=30)
    try:
        handle.die.remote().result(timeout=30)
    except Exception:
        pass  # the replica just died mid-call
    # The controller's reconcile loop replaces the dead replica.
    deadline = time.time() + 60
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = handle.remote().result(timeout=10)
            break
        except Exception:
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1


def test_batching(serve_cluster):
    serve = serve_cluster

    @serve.deployment(max_ongoing_requests=16)
    class Batcher:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def __call__(self, items):
            return [("batch", len(items), x) for x in items]

    handle = serve.run(Batcher.bind(), name="batch", route_prefix="/batch")
    responses = [handle.remote(i) for i in range(8)]
    results = [r.result(timeout=30) for r in responses]
    assert {r[2] for r in results} == set(range(8))
    # At least some calls were coalesced into a batch > 1.
    assert max(r[1] for r in results) > 1


def test_redeploy_rolls_out_new_version(serve_cluster):
    serve = serve_cluster

    @serve.deployment(name="V")
    def v1():
        return "one"

    handle = serve.run(v1.bind(), name="app", route_prefix="/v")
    assert handle.remote().result(timeout=30) == "one"

    @serve.deployment(name="V")
    def v2():
        return "two"

    handle = serve.run(v2.bind(), name="app", route_prefix="/v")
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if handle.remote().result(timeout=10) == "two":
                break
        except Exception:
            pass
        time.sleep(0.3)
    assert handle.remote().result(timeout=30) == "two"


def test_autoscaling_scale_up(serve_cluster):
    import ray_tpu

    serve = serve_cluster

    @serve.deployment(
        max_ongoing_requests=4,
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_ongoing_requests": 1,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 60.0,
        },
    )
    class Slow:
        def __call__(self):
            time.sleep(1.0)
            return os.getpid()

    handle = serve.run(Slow.bind(), name="slow", route_prefix="/slow")
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    assert len(ray_tpu.get(controller.get_replica_names.remote("slow#Slow"))) == 1

    # Sustained concurrent load >> target_ongoing_requests per replica.
    deadline = time.time() + 45
    grew = False
    pending = []
    while time.time() < deadline and not grew:
        pending = [p for p in pending if not _done(p)][:16]
        while len(pending) < 8:
            pending.append(handle.remote())
        names = ray_tpu.get(controller.get_replica_names.remote("slow#Slow"))
        grew = len(names) > 1
        time.sleep(0.3)
    assert grew, "autoscaler never added a replica under load"


def _done(resp):
    try:
        resp.result(timeout=0.01)
        return True
    except Exception:
        return False


@pytest.mark.fast
def test_request_metrics_and_latency_histogram_export(serve_cluster):
    """Serve telemetry: per-deployment request counters, queue/in-flight
    gauges and latency histograms flow replica -> worker metrics flush ->
    GCS -> Prometheus /metrics, and /api/serve summarizes them."""
    serve = serve_cluster

    @serve.deployment
    def tick(x):
        time.sleep(0.01)
        return x

    handle = serve.run(tick.bind(), name="metrics", route_prefix="/metrics-app")
    for i in range(6):
        assert handle.remote(i).result(timeout=30) == i

    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    port = w.gcs.ping()["metrics_port"]
    deadline = time.time() + 30
    text = ""
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            text = r.read().decode()
        if ("ray_tpu_serve_request_latency_seconds_bucket" in text
                and "ray_tpu_serve_handle_latency_seconds_bucket" in text
                # the gauges can land one metrics flush behind the
                # histograms (RTPU_metrics_report_period_ms rate-limits
                # the push) — wait for every asserted series
                and "ray_tpu_serve_queue_depth" in text
                and "ray_tpu_serve_inflight_requests" in text):
            break
        time.sleep(0.5)
    # replica-side series, labeled by deployment
    assert 'ray_tpu_serve_requests_total{' in text
    assert 'deployment="metrics#tick"' in text
    assert "ray_tpu_serve_request_latency_seconds_bucket" in text
    assert "ray_tpu_serve_request_latency_seconds_count" in text
    # caller-side end-to-end histogram (flushed by the driver worker)
    assert "ray_tpu_serve_handle_latency_seconds_bucket" in text
    # gauges ride the replica's 0.5s push loop
    assert "ray_tpu_serve_queue_depth" in text
    assert "ray_tpu_serve_inflight_requests" in text

    # structured summary over the same series
    from ray_tpu.dashboard.head import DashboardHead

    head = DashboardHead(w.gcs.address)
    status, payload = head._collect("/api/serve", "GET", None, {})
    assert status == 200
    dep = payload["deployments"]["metrics#tick"]
    assert dep["requests_total"] >= 6
    assert dep["errors_total"] == 0
    assert dep["replicas"] >= 1
    lat = dep["latency_seconds"]
    assert lat["count"] >= 6
    assert lat["mean"] >= 0.01 * 0.5
    assert lat["p50"] is not None


@pytest.mark.fast
def test_request_error_counter(serve_cluster):
    serve = serve_cluster

    @serve.deployment
    def boom():
        raise ValueError("nope")

    handle = serve.run(boom.bind(), name="errs", route_prefix="/errs")
    for _ in range(2):
        try:
            handle.remote().result(timeout=30)
            assert False, "expected error"
        except Exception:
            pass

    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    deadline = time.time() + 30
    recs = []
    while time.time() < deadline:
        recs = [
            r for r in w.gcs.call(
                "GetUserMetrics",
                {"prefix": "ray_tpu_serve_request_errors_total"},
            )["records"]
            if r["labels"].get("deployment") == "errs#boom"
        ]
        if recs and sum(r["value"] for r in recs) >= 2:
            break
        time.sleep(0.5)
    assert recs and sum(r["value"] for r in recs) >= 2
