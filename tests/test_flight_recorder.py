"""Flight recorder, stall watchdog, incidents, and `ray-tpu debug` forensics.

Contracts under test:
  - the ring buffer keeps the TAIL under overflow, in order, cheaply
    (tier-1 overhead guard: always-on recording must stay <2% of
    small-task throughput — bounded here per-event);
  - an artificially stuck task raises a GCS incident with captured stacks;
  - `debug dump` on a 2-node cluster yields one archive containing
    flight-recorder events from BOTH raylets plus state listings/stacks;
  - a SIGKILLed actor's ActorDiedError carries the worker's last
    flight-recorder events (periodic flush → raylet tail attach);
  - timeline: a terminal task event whose RUNNING was dropped renders as
    a Chrome instant event instead of vanishing;
  - state API: `limit` applies server-side; list_tasks has a
    detail=False fast path.
"""

import json
import os
import signal
import time

import pytest

from ray_tpu._private import flight_recorder as fr


# ------------------------------------------------------------- ring buffer


@pytest.mark.fast
def test_ring_overflow_keeps_ordered_tail():
    r = fr.FlightRecorder(64)
    for i in range(1000):
        r.record("task.running", i.to_bytes(4, "big"), f"t{i}")
    snap = r.snapshot()
    assert len(snap) == 64
    seqs = [t[0] for t in snap]
    assert seqs == sorted(seqs)  # append order preserved
    # the TAIL survives: the newest event is the last recorded one
    assert snap[-1][4] == "t999"
    assert snap[0][4] == f"t{1000 - 64}"
    dumped = r.dump()
    assert dumped[-1]["event"] == "task.running"
    assert dumped[-1]["a"] == (999).to_bytes(4, "big").hex()


@pytest.mark.fast
def test_ring_dump_limit_and_formatting():
    r = fr.FlightRecorder(128)
    r.record("obj.put", b"\xab\xcd", 4096)
    r.record("actor.state", b"\x01", "ALIVE")
    out = r.dump(limit=1)
    assert len(out) == 1 and out[0]["event"] == "actor.state"
    full = r.dump()
    assert full[0]["a"] == "abcd" and full[0]["b"] == 4096


@pytest.mark.fast
def test_ring_flush_to_file_is_incremental(tmp_path):
    r = fr.FlightRecorder(32)
    path = str(tmp_path / "flight.jsonl")
    r.record("task.pending", b"\x01", "a")
    assert r.flush_to_file(path) == 1
    r.record("task.running", b"\x01", "a")
    r.record("task.finished", b"\x01", "a")
    assert r.flush_to_file(path) == 2  # only the new events append
    assert r.flush_to_file(path) == 0  # idempotent when nothing new
    lines = [json.loads(l) for l in open(path)]
    assert [l["event"] for l in lines] == [
        "task.pending", "task.running", "task.finished"]
    tail = fr.read_tail_file(path, limit=2)
    assert [e["event"] for e in tail] == ["task.running", "task.finished"]
    assert "task.finished" in fr.format_tail(tail)


@pytest.mark.fast
def test_recorder_overhead_smoke():
    """Tier-1 guard for the always-on recorder: bound the per-event cost.

    Budget: the control plane runs ~1k-10k small tasks/s with ~6 recorded
    events per task; <2% of a 1 ms task is 20 µs, i.e. ~3.3 µs/event. The
    ring append is an order of magnitude under that; trip only on a
    catastrophic regression (a lock, formatting on the hot path...).
    The A/B microbench rides `microbench.py --only` in the slow marker
    below; this deterministic bound is the tier-1 smoke.
    """
    r = fr.FlightRecorder(4096)
    tid = b"\x01" * 16
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        r.record("task.running", tid, "bench")
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 3.3e-6, (
        f"flight-recorder append costs {per_event * 1e6:.2f} µs/event — "
        "over the <2%-of-small-task budget")


@pytest.mark.slow
def test_recorder_microbench_ab():
    """A/B the real small-task path with the recorder on vs off, riding
    `microbench.py --only single_client_tasks_async --quick`. The floor is
    loose (this box swings ±25-30% run to run); the deterministic per-event
    bound above is the sharp guard."""
    import subprocess
    import sys

    def run(flag):
        env = dict(os.environ, RTPU_flight_recorder=flag,
                   JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "microbench.py", "--quick",
             "--only", "single_client_tasks_async"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])[
            "single_client_tasks_async"]

    # median-of-3 per arm: single quick reps on this box swing ±25-30%
    off = sorted(run("0") for _ in range(3))[1]
    on = sorted(run("1") for _ in range(3))[1]
    assert on > off * 0.7, f"recorder on: {on}/s vs off: {off}/s"


# ----------------------------------------------------------- runtime events


def test_runtime_populates_ring_and_dump_rpc(ray_start_regular):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(3)]) == [1, 2, 3]
    ray_tpu.put(b"x" * (1 << 20))
    events = fr.dump()
    names = {e["event"] for e in events}
    assert "task.pending" in names and "obj.put" in names
    # the raylet's DumpFlightRecorder fans in its workers' rings
    w = worker_mod.global_worker
    node = w.gcs.get_all_node_info()[0]
    from ray_tpu.util.state import _fanout_raylets

    [(n, reply)] = _fanout_raylets(
        None, "DumpFlightRecorder", timeout=30,
        payload={"limit": 500, "include_workers": True})
    raylet_names = {e["event"] for e in reply["events"]}
    assert "lease.grant" in raylet_names or "worker.spawn" in raylet_names
    assert reply["workers"], "no worker rings collected"
    worker_names = {
        e["event"] for wrep in reply["workers"] for e in wrep["events"]
    }
    assert "task.running" in worker_names


# ------------------------------------------------------------- watchdog


def test_watchdog_fires_incident_with_stacks(monkeypatch, shutdown_only):
    """An artificially stuck task must surface as a GCS incident with
    captured stacks while it is still hanging."""
    monkeypatch.setenv("RTPU_watchdog_interval_s", "0.5")
    monkeypatch.setenv("RTPU_watchdog_task_timeout_s", "2")
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def stuck():
        time.sleep(120)

    ref = stuck.remote()
    # Both watchdogs (driver + raylet) fire for this hang; under load the
    # driver one can trip while the task is still queued (no lease → no
    # remote stack yet), so poll until SOME incident's stacks show the
    # stuck task's frame — the raylet-side probe guarantees one appears
    # once the task is actually executing.
    deadline = time.time() + 60
    incidents = []

    def all_stacks():
        return [s for i in incidents for s in (i.get("stacks") or [])]

    while time.time() < deadline:
        incidents = state.list_incidents(detail=True)
        if any("stuck" in (s.get("folded") or "") for s in all_stacks()):
            break
        time.sleep(0.5)
    assert incidents, "watchdog never published an incident"
    kinds = {i["kind"] for i in incidents}
    assert kinds & {"stuck_task", "no_progress"}
    assert all(i["status"] == "open" for i in incidents)
    assert any(i.get("ring") for i in incidents), \
        "no incident carries a flight-recorder snapshot"
    stacks = all_stacks()
    assert any(s.get("folded") for s in stacks), f"no stacks captured: {stacks}"
    # the hang itself is visible: the stuck task's frame appears in a
    # captured stack (time.sleep is a C frame; its Python caller `stuck`
    # is what sample_stacks sees)
    assert any("stuck" in (s.get("folded") or "") for s in stacks), stacks
    # `ray-tpu status`-style count sees it without fetching detail
    assert state.count_open_incidents() >= 1
    del ref


def test_watchdog_train_stall(monkeypatch, shutdown_only):
    """A StepRecorder that recorded steps and went silent raises a
    train_stall incident from the process hosting it."""
    monkeypatch.setenv("RTPU_watchdog_interval_s", "0.5")
    monkeypatch.setenv("RTPU_watchdog_step_timeout_s", "1")
    monkeypatch.setenv("RTPU_watchdog_task_timeout_s", "600")
    import ray_tpu
    from ray_tpu.train import _telemetry
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)
    rec = _telemetry.StepRecorder(emit_metrics=False, emit_spans=False)
    _telemetry.set_current_recorder(rec)
    try:
        rec.record_step(0.01, tokens=128)
        # ... then silence: the driver-side watchdog hosts this recorder
        deadline = time.time() + 30
        found = []
        while time.time() < deadline:
            found = [i for i in state.list_incidents()
                     if i["kind"] == "train_stall"]
            if found:
                break
            time.sleep(0.5)
        assert found, "train_stall incident never published"
        assert "silent" in found[0]["detail"]
    finally:
        _telemetry.set_current_recorder(None)


# ------------------------------------------------- dead-actor forensics


def test_sigkilled_actor_error_carries_flight_tail(shutdown_only):
    import ray_tpu
    from ray_tpu.exceptions import ActorDiedError
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class A:
        def ping(self):
            return os.getpid()

    a = A.remote()
    pid = ray_tpu.get(a.ping.remote())
    # generate some flight events in the actor worker, then let the 1s
    # flush cadence persist them before the un-catchable SIGKILL
    for _ in range(3):
        ray_tpu.get(a.ping.remote())
    time.sleep(2.5)
    os.kill(pid, signal.SIGKILL)
    # the raylet reaps the worker, reads its flight file tail, and the
    # death cause (with the tail) reaches the next caller's error
    deadline = time.time() + 40
    msg = ""
    while time.time() < deadline:
        try:
            ray_tpu.get(a.ping.remote(), timeout=5)
        except ActorDiedError as e:
            msg = str(e)
            if "flight-recorder" in msg:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert "flight-recorder" in msg, f"no flight tail in: {msg!r}"
    assert "task." in msg  # the tail shows actual task events
    # the failure is also on the state API
    dead = state.list_actors(filters=[("state", "=", "DEAD")])
    assert any("flight-recorder" in (d.get("death_cause") or "")
               for d in dead)


# --------------------------------------------------- debug dump (2 nodes)


def test_debug_dump_two_node_archive(tmp_path, shutdown_only):
    import zipfile

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.scripts import collect_debug_dump, cmd_debug

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"resources": {"CPU": 2, "n1": 1}},
    )
    cluster.add_node(resources={"CPU": 2, "n2": 1}, node_name="n2")
    try:
        cluster.wait_for_nodes()
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote
        def where():
            return os.getpid()

        # touch BOTH nodes so both raylets have flight events
        ray_tpu.get([
            where.options(resources={"n1": 1}).remote(),
            where.options(resources={"n2": 1}).remote(),
        ])
        files = collect_debug_dump(cluster.address, ring_limit=500,
                                   stack_duration=0.2)
        flight = {k: v for k, v in files.items()
                  if k.startswith("flight/node_")}
        assert len(flight) == 2, f"expected 2 per-node rings, got {list(files)}"
        for name, text in flight.items():
            payload = json.loads(text)
            assert payload["raylet_events"], f"{name} has an empty raylet ring"
            events = {e["event"] for e in payload["raylet_events"]}
            assert events & {"lease.grant", "worker.spawn", "lease.return"}
        assert "incidents.json" in files
        assert "state/tasks.json" in files and "state/nodes.json" in files
        assert len(json.loads(files["state/nodes.json"])) == 2
        stacks = [k for k in files if k.startswith("stacks/")]
        assert len(stacks) == 2
        assert any("==" in files[k] for k in stacks), "no worker stacks sampled"

        # the CLI wraps the same collection into one zip archive
        class Args:
            debug_cmd = "dump"
            address = cluster.address
            output = str(tmp_path / "dump.zip")
            ring_limit = 500

        cmd_debug(Args())
        with zipfile.ZipFile(Args.output) as z:
            names = z.namelist()
            assert sum(1 for n in names
                       if n.startswith("flight/node_")) == 2
            assert "flight/gcs.json" in names  # the control plane's ring
            assert "incidents.json" in names
    finally:
        import ray_tpu as _rt

        if _rt.is_initialized():
            _rt.shutdown()
        cluster.shutdown()


# ----------------------------------------------------- timeline satellite


@pytest.mark.fast
def test_timeline_terminal_without_running_renders_instant():
    from ray_tpu._private.timeline import chrome_trace_events

    events = [
        # RUNNING dropped (ring overflow / flush loss): only the terminal
        # event survived
        {"task_id": "t1", "name": "lost", "state": "FINISHED", "ts": 10.0,
         "node_id": "n", "worker_id": "w", "job_id": "j"},
        # healthy pair still renders the X duration event
        {"task_id": "t2", "name": "ok", "state": "RUNNING", "ts": 11.0,
         "node_id": "n", "worker_id": "w", "job_id": "j"},
        {"task_id": "t2", "name": "ok", "state": "FINISHED", "ts": 12.0,
         "node_id": "n", "worker_id": "w", "job_id": "j"},
    ]
    out = chrome_trace_events(events)
    instants = [e for e in out if e["ph"] == "i" and "lost" in e["name"]]
    assert len(instants) == 1
    assert instants[0]["args"]["state"] == "FINISHED"
    assert "missing" in instants[0]["args"]["note"]
    assert any(e["ph"] == "X" and e["name"] == "ok" for e in out)
    # a FAILED terminal without RUNNING is visible too
    out2 = chrome_trace_events([
        {"task_id": "t3", "name": "boom", "state": "FAILED", "ts": 1.0,
         "node_id": "n", "worker_id": "w", "job_id": "j", "error": "x"},
    ])
    assert any(e["ph"] == "i" and "boom" in e["name"] for e in out2)


# ------------------------------------------------------ state satellites


def test_list_tasks_server_side_limit_and_detail(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state

    @ray_tpu.remote
    def tick(i):
        return i

    ray_tpu.get([tick.remote(i) for i in range(6)])
    deadline = time.time() + 15
    tasks = []
    while time.time() < deadline:
        tasks = state.list_tasks()
        if sum(1 for t in tasks if t["state"] == "FINISHED") >= 6:
            break
        time.sleep(0.3)
    assert len(tasks) >= 6
    # server-side limit: exactly N rows cross the wire
    assert len(state.list_tasks(limit=2)) == 2
    # detail=False fast path: identity/state only
    lite = state.list_tasks(detail=False)
    assert lite and "error_message" not in lite[0]
    assert {"task_id", "name", "state"} <= set(lite[0])
    # detail rows keep attribution
    full = state.list_tasks()
    assert "error_message" in full[0] and "worker_id" in full[0]
    # other listings accept server-side limits too
    assert len(state.list_nodes(limit=1)) == 1
