"""Native shared-memory store unit tests
(modeled on reference src/ray/object_manager/plasma/test/)."""

import os

import numpy as np
import pytest

from ray_tpu._native.plasma import PlasmaClient, PlasmaOOM


@pytest.fixture
def store():
    name = f"/rtpu_test_{os.getpid()}_{os.urandom(4).hex()}"
    client = PlasmaClient(name, capacity=32 * 1024 * 1024, create=True)
    yield client
    client.close(unmap=True)
    PlasmaClient.unlink(name)


def _oid():
    return os.urandom(20)


def test_create_seal_get(store):
    oid = _oid()
    data = np.arange(1000, dtype=np.int64)
    buf = store.create(oid, data.nbytes)
    np.frombuffer(buf, dtype=np.int64)[:] = data
    buf.release()
    store.seal(oid)
    view = store.get(oid)
    assert np.array_equal(np.frombuffer(view, dtype=np.int64), data)
    view.release()
    store.release(oid)


def test_get_missing(store):
    assert store.get(_oid()) is None
    assert not store.contains(_oid())


def test_put_blob_zero_byte_and_multidim_views(store):
    """put_blob takes any bytes-like view, including empty multi-dim
    buffers (cast(\"B\") rejects zeros-in-shape views — regression)."""
    oid = _oid()
    assert store.put_blob(oid, np.zeros((0, 3), dtype=np.float64))
    view = store.get(oid)
    assert view is not None and view.nbytes == 0
    view.release()
    store.release(oid)

    oid2 = _oid()
    arr = np.arange(12, dtype=np.float64).reshape(3, 4)
    assert store.put_blob(oid2, memoryview(arr))
    view = store.get(oid2)
    assert np.array_equal(
        np.frombuffer(view, dtype=np.float64).reshape(3, 4), arr
    )
    view.release()
    store.release(oid2)


def test_unsealed_not_gettable(store):
    oid = _oid()
    buf = store.create(oid, 100)
    buf.release()
    assert store.get(oid) is None
    store.abort(oid)


def test_double_create_rejected(store):
    oid = _oid()
    b = store.create(oid, 10)
    b.release()
    store.seal(oid)
    with pytest.raises(FileExistsError):
        store.create(oid, 10)


def test_delete_frees_space(store):
    oid = _oid()
    assert store.put_blob(oid, b"x" * 1_000_000)
    used_before = store.stats()["used_bytes"]
    assert store.delete(oid)
    assert store.stats()["used_bytes"] < used_before
    assert not store.contains(oid)


def test_pending_delete_deferred_while_pinned(store):
    oid = _oid()
    store.put_blob(oid, b"y" * 1000)
    view = store.get(oid)  # pins
    assert not store.delete(oid)  # deferred
    assert bytes(view[:4]) == b"yyyy"  # data still valid under the view
    view.release()
    store.release(oid)  # last unpin reclaims
    assert not store.contains(oid)


def test_lru_eviction_under_pressure(store):
    for _ in range(40):
        assert store.put_blob(_oid(), b"z" * (2 * 1024 * 1024))
    stats = store.stats()
    assert stats["evicted_count"] > 0
    assert stats["used_bytes"] <= stats["capacity_bytes"]


def test_pinned_objects_survive_eviction(store):
    oid = _oid()
    store.put_blob(oid, b"k" * 1024)
    view = store.get(oid)  # pin
    for _ in range(40):
        store.put_blob(_oid(), b"z" * (2 * 1024 * 1024))
    assert store.contains(oid)
    assert bytes(view[:4]) == b"kkkk"
    view.release()
    store.release(oid)


def test_oom_when_everything_pinned(store):
    oid = _oid()
    store.put_blob(oid, b"a" * (30 * 1024 * 1024))
    view = store.get(oid)
    with pytest.raises(PlasmaOOM):
        store.create(_oid(), 30 * 1024 * 1024)
    view.release()
    store.release(oid)


def test_cross_client_visibility(store):
    other = PlasmaClient(store.name)
    oid = _oid()
    store.put_blob(oid, b"shared")
    view = other.get(oid)
    assert bytes(view) == b"shared"
    view.release()
    other.release(oid)
    other.close()


def test_free_list_coalescing(store):
    # fill, delete all, then a single allocation of most of the arena must fit
    oids = [_oid() for _ in range(10)]
    for oid in oids:
        store.put_blob(oid, b"c" * (2 * 1024 * 1024))
    for oid in oids:
        store.delete(oid)
    big = _oid()
    buf = store.create(big, 24 * 1024 * 1024)
    buf.release()
    store.seal(big)
    assert store.contains(big)


def test_data_offsets_64_byte_aligned(store):
    # ADVICE r1: zero-copy buffers must be truly 64-byte aligned in the shared
    # segment (Block header is padded to 64 bytes so data offsets stay aligned).
    import ctypes

    for size in (1, 63, 64, 1000, 4096 + 17):
        oid = _oid()
        buf = store.create(oid, size)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        assert addr % 64 == 0, f"size={size} addr={addr:#x}"
        buf.release()
        store.seal(oid)


def test_owner_death_recovery(store):
    """A process that dies while holding the robust mutex must not wedge or
    corrupt the store: the next locker rebuilds the free list and continues."""
    import multiprocessing

    # Populate some state first.
    keep = _oid()
    store.put_blob(keep, b"survivor" * 100)

    def _die_holding_lock(name):
        c = PlasmaClient(name)
        c._test_lock_and_abandon()
        os._exit(1)

    ctx = multiprocessing.get_context("fork")
    p = ctx.Process(target=_die_holding_lock, args=(store.name,))
    p.start()
    p.join(timeout=30)
    assert p.exitcode == 1

    # Next operation recovers via EOWNERDEAD instead of deadlocking.
    oid = _oid()
    store.put_blob(oid, b"after-recovery" * 10)
    view = store.get(oid)
    assert bytes(view) == b"after-recovery" * 10
    view.release()
    store.release(oid)
    view = store.get(keep)
    assert bytes(view) == b"survivor" * 100
    view.release()
    store.release(keep)
    assert store.recovered_count() >= 1
    assert not store.poisoned()
