"""Distributed tracing: span recording + cross-process context propagation.

Reference contract: tracing is opt-in and the trace context follows remote
calls into workers (python/ray/util/tracing/tracing_helper.py — the
injected _ray_trace_ctx); spans land in the timeline.
"""

import time

import pytest


def test_spans_record_and_propagate(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import tracing

    tracing.enable()
    try:

        @ray_tpu.remote
        def traced_task():
            from ray_tpu.util import tracing as t

            ctx = t.current_context()
            with t.span("inner-work", {"k": "v"}):
                time.sleep(0.01)
            return ctx

        with tracing.span("driver-root") as root:
            worker_ctx = ray_tpu.get(traced_task.remote())

        # The worker saw the SAME trace id as the driver's root span.
        assert worker_ctx is not None
        assert worker_ctx["trace_id"] == root["trace_id"]
        # ...and its parent span is the driver's root span.
        assert worker_ctx.get("span_id") == root["span_id"]

        # Spans flush with the task events and appear in the timeline.
        deadline = time.time() + 15
        spans = []
        while time.time() < deadline:
            events = ray_tpu.timeline()
            spans = [e for e in events if e.get("cat") == "span"]
            if len(spans) >= 2:
                break
            time.sleep(0.3)
        names = {s["name"] for s in spans}
        assert {"driver-root", "inner-work"} <= names
        inner = next(s for s in spans if s["name"] == "inner-work")
        assert inner["args"]["trace_id"] == root["trace_id"]
        assert inner["args"]["k"] == "v"
        assert inner["dur"] >= 0.01 * 1e6 * 0.5
    finally:
        tracing.disable()


def test_actor_trace_propagation(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import tracing

    tracing.enable()
    try:

        @ray_tpu.remote
        class Traced:
            def ctx(self):
                from ray_tpu.util import tracing as t

                return t.current_context()

        a = Traced.remote()
        with tracing.span("actor-call-root") as root:
            ctx = ray_tpu.get(a.ctx.remote())
        assert ctx is not None and ctx["trace_id"] == root["trace_id"]
    finally:
        tracing.disable()


def test_disabled_is_no_op(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import tracing

    assert not tracing.is_enabled()
    with tracing.span("nothing") as s:
        assert s is None

    @ray_tpu.remote
    def f():
        from ray_tpu.util import tracing as t

        return t.current_context()

    assert ray_tpu.get(f.remote()) is None


def test_span_exporter_seam(ray_start_regular):
    """Pluggable exporter receives finished spans (reference:
    tracing_helper.py OTel wiring; enable_otel_export no-ops without the
    SDK installed)."""
    from ray_tpu.util import tracing

    got = []
    tracing.set_span_exporter(got.append)
    try:
        tracing.enable()
        with tracing.span("outer", {"k": "v"}):
            with tracing.span("inner"):
                pass
        names = [s["name"] for s in got]
        assert names == ["inner", "outer"]
        inner, outer = got
        assert inner["parent_span_id"] == outer["span_id"]
        assert inner["trace_id"] == outer["trace_id"]
        assert outer["attributes"] == {"k": "v"}
        # exporter exceptions never propagate to user code
        tracing.set_span_exporter(lambda s: 1 / 0)
        with tracing.span("safe"):
            pass
    finally:
        tracing.set_span_exporter(None)
        tracing.disable()
