"""Offline RL through ray_tpu.data: episode recording to parquet, BC and
MARWIL training (reference: rllib/offline/offline_data.py:18,
rllib/algorithms/bc + marwil), and the APPO async learner.

The expert for CartPole is the classic angle-plus-angular-velocity
controller — near-200 return, trivially imitable, so BC reaching the
threshold proves the data plane + learner loop, not RL luck."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.offline import (
    batch_to_numpy,
    read_experiences,
    record_episodes,
)


def expert_policy(obs):
    # push right iff the pole is falling right
    return 1 if obs[2] + 0.5 * obs[3] > 0 else 0


@pytest.fixture
def offline_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_record_and_read_roundtrip(tmp_path, offline_cluster):
    stats = record_episodes(
        "CartPole-v1", expert_policy, 8, str(tmp_path / "exp"), seed=0)
    assert stats["episodes"] == 8
    assert stats["mean_return"] > 150  # the scripted expert is good
    ds = read_experiences(str(tmp_path / "exp"))
    total = 0
    saw_cols = set()
    for batch in ds.iter_batches(batch_size=256):
        b = batch_to_numpy(batch)
        saw_cols.update(b)
        total += len(b["action"])
        assert b["obs"].shape[1] == 4
        assert np.isfinite(b["return_to_go"]).all()
    assert total == stats["steps"]
    assert {"obs", "action", "reward", "return_to_go",
            "episode_id"} <= saw_cols


def test_bc_learns_cartpole_from_parquet(tmp_path, offline_cluster):
    from ray_tpu.rllib import BCConfig

    record_episodes("CartPole-v1", expert_policy, 40,
                    str(tmp_path / "exp"), seed=0)
    algo = (
        BCConfig()
        .environment("CartPole-v1")
        .offline_data(str(tmp_path / "exp"))
        .training(lr=3e-3, train_batch_size=512, minibatches_per_iter=24)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(12):
        metrics = algo.train()
        ev = algo.evaluate(num_episodes=5)
        best = max(best, ev["episode_return_mean"])
        if best >= 150:
            break
    assert best >= 150, f"BC failed to imitate the expert: best={best:.1f}"
    assert metrics["mean_logp"] > -0.35  # actions confidently imitated


def test_marwil_upweights_high_return_actions(tmp_path, offline_cluster):
    """The advantage-weighted loss: on a mixed dataset whose STEP counts
    are balanced between the expert and the anti-expert (inverted
    controller — every action label conflicts), BC imitates a coin flip
    while MARWIL's exponential advantage weighting recovers the expert."""
    from ray_tpu.rllib import BCConfig, MARWILConfig

    def anti_expert(obs):
        return 1 - expert_policy(obs)

    # expert episodes run ~300-500 steps, anti-expert ~10: balance steps
    path = str(tmp_path / "mixed")
    s1 = record_episodes("CartPole-v1", expert_policy, 3,
                         path + "/expert", seed=100)
    n_bad = max(1, int(s1["steps"] / 10))
    s2 = record_episodes("CartPole-v1", anti_expert, n_bad,
                         path + "/anti", seed=500)
    # labels genuinely conflict, with comparable step mass
    assert 0.5 <= s2["steps"] / s1["steps"] <= 2.0, (s1, s2)
    ds_path = [path + "/expert", path + "/anti"]

    def train_eval(config_cls):
        algo = (
            config_cls()
            .environment("CartPole-v1")
            .offline_data(ds_path)
            .training(lr=3e-3, train_batch_size=512,
                      minibatches_per_iter=24)
            .debugging(seed=0)
            .build()
        )
        last = {}
        for _ in range(10):
            last = algo.train()
        ev = algo.evaluate(num_episodes=8)
        return ev["episode_return_mean"], last

    marwil_ret, marwil_metrics = train_eval(MARWILConfig)
    bc_ret, _ = train_eval(BCConfig)
    # the exponential weights are genuinely non-uniform on conflicted data
    assert marwil_metrics["mean_weight"] > 0
    assert marwil_ret > 150, f"MARWIL failed to recover the expert: {marwil_ret:.1f}"
    assert marwil_ret > bc_ret + 50, (
        f"MARWIL ({marwil_ret:.1f}) should beat BC ({bc_ret:.1f}) on conflicted data")


def test_appo_cartpole_learns(offline_cluster, monkeypatch):
    """APPO (async PPO on the IMPALA engine) reaches the CartPole
    threshold; its target network + clipped surrogate run in one jit."""
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=4, num_envs_per_env_runner=8,
                     rollout_fragment_length=32)
        .training(lr=3e-3, entropy_coeff=0.01, train_iter_env_steps=6144,
                  clip_param=0.3, target_update_freq=4)
        .debugging(seed=0)
        .build()
    )
    try:
        best = 0.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 150:
                break
        assert best >= 150, f"APPO failed to learn CartPole: best={best:.1f}"
        assert result["learner/kl"] >= 0.0  # target-policy KL is reported
    finally:
        algo.stop()
