"""JaxTrainer end-to-end (modeled on reference python/ray/train/tests/
test_data_parallel_trainer.py): real cluster, real worker actors, real jax."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def ray_4cpu(tmp_path):
    ray_tpu.init(num_cpus=4)
    yield str(tmp_path)
    ray_tpu.shutdown()


def test_report_rounds_and_context(ray_4cpu):
    def loop(config):
        ctx = train.get_context()
        assert ctx.get_world_size() == 2
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "lr": config["lr"]})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.1},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=ray_4cpu, name="ctx"),
        jax_config=JaxConfig(distributed=False),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    assert result.metrics["rank"] == 0  # rank-0 metrics win
    assert len(result.metrics_history) == 3


def test_checkpoint_save_and_restore(ray_4cpu):
    def loop(config):
        import json

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "state.json")) as f:
                start = json.load(f)["step"] + 1
        for step in range(start, start + 2):
            if ctx.get_world_rank() == 0:
                import tempfile

                d = tempfile.mkdtemp()
                with open(os.path.join(d, "state.json"), "w") as f:
                    json.dump({"step": step}, f)
                train.report({"step": step}, checkpoint=Checkpoint(d))
            else:
                train.report({"step": step})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=ray_4cpu, name="ckpt",
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
        jax_config=JaxConfig(distributed=False),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 1
    assert result.checkpoint is not None

    # resume: picks up where the checkpoint left off
    trainer2 = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=ray_4cpu, name="ckpt2"),
        jax_config=JaxConfig(distributed=False),
        resume_from_checkpoint=result.checkpoint,
    )
    result2 = trainer2.fit()
    assert result2.metrics["step"] == 3


def test_worker_error_surfaces(ray_4cpu):
    def loop(config):
        ctx = train.get_context()
        train.report({"ok": True})
        if ctx.get_world_rank() == 1:
            raise ValueError("boom at rank 1")
        train.report({"ok": True})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=ray_4cpu, name="err"),
        jax_config=JaxConfig(distributed=False),
    )
    with pytest.raises(train.TrainingFailedError, match="boom at rank 1"):
        trainer.fit()


def test_jax_distributed_spmd_training(ray_4cpu):
    """2 worker processes x 4 virtual CPU devices = one 8-device dp mesh;
    the sharded GPT-2 step must train with per-process batch shards."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.gpt2 import GPT2Config
        from ray_tpu.parallel.mesh import make_mesh
        from ray_tpu.parallel.train_step import TrainStep

        assert jax.process_count() == 2
        assert len(jax.devices()) == 8

        cfg = GPT2Config.tiny(use_flash_attention=False, dtype=jnp.float32)
        mesh = make_mesh({"dp": 8})
        ts = TrainStep(cfg, mesh, learning_rate=1e-3)
        state = ts.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(train.get_context().get_world_rank())
        B_local, T = 4, 32
        for _ in range(2):
            idx = rng.integers(0, cfg.vocab_size, (B_local, T)).astype(np.int32)
            batch_local = {
                "idx": idx, "targets": np.roll(idx, -1, axis=1),
            }
            batch = jax.make_array_from_process_local_data(
                ts.batch_sharding,
                batch_local["idx"],
            )
            tgt = jax.make_array_from_process_local_data(
                ts.batch_sharding,
                batch_local["targets"],
            )
            state, m = ts.step(state, {"idx": batch, "targets": tgt})
        train.report({"loss": float(m["loss"])})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=ray_4cpu, name="spmd"),
        jax_config=JaxConfig(
            distributed=True,
            env={
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            },
        ),
    )
    result = trainer.fit()
    assert np.isfinite(result.metrics["loss"])


def test_group_restart_on_failure(ray_4cpu):
    marker = os.path.join(ray_4cpu, "died_once")

    def loop(config):
        import json
        import tempfile

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                start = json.load(f)["step"] + 1
        for step in range(start, 4):
            if ctx.get_world_rank() == 0:
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "s.json"), "w") as f:
                    json.dump({"step": step}, f)
                train.report({"step": step}, checkpoint=Checkpoint(d))
            else:
                train.report({"step": step})
            if step == 1 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                os._exit(1)  # hard-kill the worker process

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=ray_4cpu, name="restart",
            failure_config=FailureConfig(max_failures=1),
        ),
        jax_config=JaxConfig(distributed=False),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3


def test_pipelined_checkpoint_report_blocks_until_ack():
    """With pipeline_depth > 1, a checkpoint report must not return before
    the driver acked it (the checkpoint dir may be deleted right after
    report() returns — reference train/_internal/session.py:667 persists
    before returning). Metrics-only reports stay pipelined."""
    import threading
    import time

    from ray_tpu.train._session import TrainContext, _Session

    ctx = TrainContext(0, 1, 0, 1, "127.0.0.1")
    s = _Session(ctx, None, pipeline_depth=8)

    # metrics-only reports return immediately (no ack yet)
    for i in range(4):
        s.report({"step": i}, None)

    state = {"returned": False}

    def ckpt_report():
        s.report({"step": 4}, Checkpoint("/tmp"))
        state["returned"] = True

    t = threading.Thread(target=ckpt_report, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not state["returned"], "checkpoint report returned before ack"
    # driver consumes+acks the first 4 rounds: still not this report's turn
    s.ack(4)
    time.sleep(0.2)
    assert not state["returned"]
    s.ack(1)  # ack the checkpoint round itself
    t.join(timeout=5)
    assert state["returned"]
