"""Core API tests: tasks, objects, errors
(modeled on reference python/ray/tests/test_basic.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


@ray_tpu.remote
def echo(x):
    return x


@ray_tpu.remote
def add(a, b):
    return a + b


def test_simple_task(ray_start_regular):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_many_parallel_tasks(ray_start_regular):
    refs = [add.remote(i, i) for i in range(50)]
    assert ray_tpu.get(refs) == [2 * i for i in range(50)]


def test_put_get_roundtrip(ray_start_regular):
    for value in [1, "hello", {"a": [1, 2, 3]}, None, (1, 2), b"bytes"]:
        assert ray_tpu.get(ray_tpu.put(value)) == value


def test_put_get_numpy_zero_copy(ray_start_regular):
    arr = np.arange(500_000, dtype=np.float64)
    got = ray_tpu.get(ray_tpu.put(arr))
    assert np.array_equal(got, arr)


def test_large_task_return_via_plasma(ray_start_regular):
    @ray_tpu.remote
    def big():
        return np.ones((1000, 1000), dtype=np.float32)

    arr = ray_tpu.get(big.remote())
    assert float(arr.sum()) == 1_000_000.0


def test_large_task_arg(ray_start_regular):
    arr = np.ones(300_000, dtype=np.float64)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    assert ray_tpu.get(total.remote(arr)) == 300_000.0


def test_object_ref_as_arg(ray_start_regular):
    ref = ray_tpu.put(21)
    assert ray_tpu.get(add.remote(ref, 21)) == 42


def test_nested_object_ref_in_arg(ray_start_regular):
    ref = ray_tpu.put(5)

    @ray_tpu.remote
    def unwrap(d):
        return ray_tpu.get(d["ref"]) + 1

    assert ray_tpu.get(unwrap.remote({"ref": ref})) == 6


def test_chained_dependencies(ray_start_regular):
    x = add.remote(1, 1)
    y = add.remote(x, 1)
    z = add.remote(y, 1)
    assert ray_tpu.get(z) == 4


def test_task_exception(ray_start_regular):
    @ray_tpu.remote
    def fail():
        raise ValueError("expected failure")

    with pytest.raises(TaskError, match="expected failure"):
        ray_tpu.get(fail.remote())


def test_exception_propagates_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def fail():
        raise ValueError("root cause")

    # Downstream tasks receiving a failed ref also fail at get().
    downstream = add.remote(fail.remote(), 1)
    with pytest.raises(TaskError):
        ray_tpu.get(downstream)


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    refs = [sleepy.remote(0.01), sleepy.remote(5)]
    ready, pending = ray_tpu.wait(refs, num_returns=1, timeout=10)
    assert len(ready) == 1 and len(pending) == 1
    assert ray_tpu.get(ready[0]) == 0.01


def test_nested_task_submission(ray_start_regular):
    @ray_tpu.remote
    def outer(n):
        return sum(ray_tpu.get([add.remote(i, 1) for i in range(n)]))

    assert ray_tpu.get(outer.remote(4)) == 10


def test_options_override(ray_start_regular):
    assert ray_tpu.get(add.options(name="custom").remote(2, 2)) == 4


def test_num_cpus_resource(ray_start_regular):
    @ray_tpu.remote(num_cpus=2)
    def heavy():
        return "done"

    assert ray_tpu.get(heavy.remote()) == "done"


def test_kwargs(ray_start_regular):
    @ray_tpu.remote
    def kw(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(kw.remote(1, c=2)) == 13


def test_cluster_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0


def test_remote_call_direct_raises(ray_start_regular):
    with pytest.raises(TypeError):
        add(1, 2)


def test_deep_queue_batched_tasks(ray_start_regular):
    """A deep queue of tiny tasks triggers PushTasks batching; results must
    stay exact and per-ref ordered."""
    refs = [add.remote(i, 1) for i in range(400)]
    assert ray_tpu.get(refs) == [i + 1 for i in range(400)]


def test_coordinating_tasks_in_deep_queue(shutdown_only):
    """Tasks that synchronize with each other must not deadlock when deep-
    queue batching packs them onto shared leases: batched tasks execute
    concurrently, as if each had its own lease."""
    import time as _time

    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    class Signal:
        def __init__(self):
            self.sent = False

        def send(self):
            self.sent = True

        def ready(self):
            return self.sent

    sig = Signal.remote()

    # One function for every role so all tasks share a scheduling key and
    # are eligible for the same PushTasks batches.
    @ray_tpu.remote
    def step(role, s):
        import ray_tpu as rt

        if role == "wait":
            deadline = _time.time() + 60
            while not rt.get(s.ready.remote()):
                if _time.time() > deadline:
                    return False
                _time.sleep(0.01)
            return True
        if role == "send":
            rt.get(s.send.remote())
        return True

    refs = [step.remote("noop", sig) for _ in range(40)]
    refs += [step.remote("wait", sig) for _ in range(3)]
    refs += [step.remote("noop", sig) for _ in range(40)]
    refs += [step.remote("send", sig)]
    refs += [step.remote("noop", sig) for _ in range(40)]
    out = ray_tpu.get(refs, timeout=120)
    assert all(out), out
