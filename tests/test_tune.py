"""Ray-Tune-subset tests (reference: python/ray/tune/tests/)."""

import json
import os
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint
from ray_tpu.tune import (
    ASHAScheduler,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
)


@pytest.fixture(scope="module")
def tune_cluster():
    ray_tpu.init(num_cpus=18)
    yield
    ray_tpu.shutdown()


def _exp_dir():
    return tempfile.mkdtemp(prefix="rtpu_tune_")


def objective(config):
    """Converges toward config['target']; higher lr converges faster."""
    score = 0.0
    for i in range(config.get("iters", 8)):
        score += config["lr"]
        tune.report({"score": score})


def test_grid_and_random_expansion():
    from ray_tpu.tune.search_space import generate_variants

    space = {"a": tune.grid_search([1, 2, 3]), "b": tune.uniform(0, 1), "c": 7}
    variants = generate_variants(space, num_samples=2, seed=0)
    assert len(variants) == 6
    assert sorted(v["a"] for v in variants) == [1, 1, 2, 2, 3, 3]
    assert all(0 <= v["b"] <= 1 and v["c"] == 7 for v in variants)


def test_16_concurrent_trials(tune_cluster):
    from ray_tpu.train._config import RunConfig

    tuner = Tuner(
        objective,
        param_space={"lr": tune.grid_search(
            [round(0.1 * (i + 1), 1) for i in range(16)]
        )},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid16", storage_path=_exp_dir()),
    )
    grid = tuner.fit()
    assert len(grid) == 16
    assert not grid.errors
    best = grid.get_best_result("score")
    assert best.config["lr"] == 1.6
    assert best.metrics["score"] == pytest.approx(1.6 * 8)


def test_asha_early_stopping(tune_cluster):
    from ray_tpu.train._config import RunConfig

    def slow_objective(config):
        score = 0.0
        for _ in range(32):
            score += config["lr"]
            tune.report({"score": score})

    scheduler = ASHAScheduler(
        metric="score", mode="max", max_t=32, grace_period=2,
        reduction_factor=4,
    )
    tuner = Tuner(
        slow_objective,
        param_space={"lr": tune.grid_search(
            [0.01 * (i + 1) for i in range(16)]
        )},
        tune_config=TuneConfig(scheduler=scheduler),
        run_config=RunConfig(name="asha16", storage_path=_exp_dir()),
    )
    grid = tuner.fit()
    assert not grid.errors
    iters = [r.metrics.get("training_iteration", 0) for r in grid]
    # at least one winner ran to completion; whether losers get rung-stopped
    # depends on arrival order (ASHA is asynchronous), so early-stop
    # decisions are asserted deterministically in test_asha_rung_decisions
    assert max(iters) == 32, iters
    best = grid.get_best_result("score")
    assert best.config["lr"] == pytest.approx(0.16)


def test_asha_rung_decisions():
    """Deterministic unit test of the rung cutoff logic: trials arriving at
    a milestone below the top-1/rf quantile are stopped."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    sched = ASHAScheduler(metric="score", mode="max", max_t=100,
                          grace_period=4, reduction_factor=4)

    class T:
        def __init__(self, tid):
            self.id = tid

    # descending scores arriving at the milestone: first passes freely,
    # later (worse) arrivals fall below the cutoff and stop
    decisions = [
        sched.on_trial_result(None, T(f"t{i}"),
                              {"training_iteration": 4, "score": 100 - i})
        for i in range(8)
    ]
    assert decisions[0] == CONTINUE
    assert STOP in decisions[1:], decisions
    assert decisions.count(STOP) >= 4, decisions
    # a strictly better late arrival is promoted
    assert sched.on_trial_result(
        None, T("late"), {"training_iteration": 4, "score": 1000}
    ) == CONTINUE


def test_pbt_perturbation(tune_cluster):
    from ray_tpu.train._config import RunConfig

    def ckpt_objective(config):
        start = 0
        score = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "state.json")) as f:
                st = json.load(f)
            start, score = st["i"], st["score"]
        for i in range(start, 16):
            score += config["lr"]
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"i": i + 1, "score": score}, f)
            tune.report({"score": score}, checkpoint=Checkpoint(d))

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.1, 0.5, 1.0, 2.0]},
    )
    tuner = Tuner(
        ckpt_objective,
        param_space={"lr": tune.grid_search([0.1, 0.5, 1.0, 2.0])},
        tune_config=TuneConfig(scheduler=pbt),
        run_config=RunConfig(name="pbt4", storage_path=_exp_dir()),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert pbt.num_perturbations > 0
    best = grid.get_best_result("score")
    # exploiting the lr=2.0 donor means even former losers end near the top
    assert best.metrics["score"] >= 16 * 2.0 * 0.5


def test_stop_criteria_and_state_file(tune_cluster):
    from ray_tpu.train._config import RunConfig
    from ray_tpu.tune.controller import TuneController

    storage = _exp_dir()
    exp = os.path.join(storage, "stopit")

    def forever(config):
        i = 0
        while True:
            i += 1
            tune.report({"x": i})

    controller = TuneController(
        forever, [{}, {}], exp, stop={"training_iteration": 3},
    )
    trials = controller.run()
    assert all(t.state == "TERMINATED" for t in trials)
    assert all(t.iteration == 3 for t in trials)
    with open(os.path.join(exp, "experiment_state.json")) as f:
        state = json.load(f)
    assert len(state["trials"]) == 2


def test_tuner_restore_resumes_unfinished(tune_cluster):
    from ray_tpu.train._config import RunConfig

    storage = _exp_dir()

    def ckpt_objective(config):
        start = 0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "state.json")) as f:
                start = json.load(f)["i"]
        for i in range(start, 6):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"i": i + 1}, f)
            tune.report({"i": i + 1}, checkpoint=Checkpoint(d))

    tuner = Tuner(
        ckpt_objective,
        param_space={"z": tune.grid_search([1, 2])},
        run_config=RunConfig(name="resume_exp", storage_path=storage),
    )
    grid = tuner.fit()
    assert not grid.errors

    # simulate an interruption: mark trial_00001 unfinished at iteration 3
    exp = os.path.join(storage, "resume_exp")
    path = os.path.join(exp, "experiment_state.json")
    with open(path) as f:
        state = json.load(f)
    state["trials"][1]["state"] = "RUNNING"
    state["trials"][1]["iteration"] = 3
    state["trials"][1]["latest_checkpoint"] = os.path.join(
        exp, "trial_00001", "checkpoint_000002"
    )
    with open(path, "w") as f:
        json.dump(state, f)

    restored = Tuner.restore(exp, ckpt_objective)
    grid2 = restored.fit()
    assert not grid2.errors
    # trial 0 kept its result without re-running (no new reports); trial 1
    # resumed from the checkpoint at i=3 and only re-ran rounds 4..6
    assert grid2[0].metrics["i"] == 6
    assert len(grid2[0].metrics_history) == 0
    assert grid2[1].metrics["i"] == 6
    assert len(grid2[1].metrics_history) == 3


def test_trainer_fit_is_one_trial_tune_run(tune_cluster):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu import train

        for step in range(3):
            train.report({"step": step, "lr": config["lr"]})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.5},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="fit_via_tune", storage_path=_exp_dir()),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
    assert result.metrics["lr"] == 0.5
    assert len(result.metrics_history) == 3
    # the tune experiment state lives next to the trainer's checkpoints
    assert os.path.exists(
        os.path.join(trainer.experiment_dir, "experiment_state.json")
    )


def test_median_stopping_rule():
    """Unit: a trial whose running average trails the median is stopped
    after the grace period (reference: schedulers/median_stopping_rule.py)."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP, MedianStoppingRule

    class _T:
        def __init__(self, tid):
            self.id = tid
            self.iteration = 0

    sched = MedianStoppingRule(metric="score", mode="max", grace_period=2,
                               min_samples_required=3)
    good = [_T(f"g{i}") for i in range(3)]
    bad = _T("bad")
    for step in range(1, 7):
        for t in good:
            assert sched.on_trial_result(None, t, {"score": 10.0}) == CONTINUE
        decision = sched.on_trial_result(None, bad, {"score": 1.0})
    assert decision == STOP


def test_hyperband_scheduler_brackets():
    """Unit: bracket assignment round-robins; bottom scorers at a rung get
    stopped, everyone stops at max_t."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP, HyperBandScheduler

    class _T:
        def __init__(self, tid):
            self.id = tid
            self.iteration = 0

    sched = HyperBandScheduler(metric="score", mode="max", max_t=9,
                               reduction_factor=3)
    assert sched.num_brackets == 3
    trials = [_T(f"t{i}") for i in range(6)]
    # all trials report at step 1 with spread scores
    decisions = [
        sched.on_trial_result(None, t, {"score": float(i),
                                        "training_iteration": 1})
        for i, t in enumerate(trials)
    ]
    assert CONTINUE in decisions
    # a terrible score arriving at a populated rung is stopped
    late = _T("late")
    sched._assignment["late"] = 0  # same bracket as t0/t3
    d = sched.on_trial_result(None, late, {"score": -100.0,
                                           "training_iteration": 1})
    assert d == STOP
    # max_t always stops
    assert sched.on_trial_result(
        None, trials[0], {"score": 100.0, "training_iteration": 9}
    ) == STOP


def test_median_stopping_e2e(tune_cluster):
    """16 trials, half clearly worse: median stopping prunes losers while a
    winner completes."""
    from ray_tpu.train._config import RunConfig
    from ray_tpu.tune.schedulers import MedianStoppingRule

    def objective(config):
        score = 0.0
        for _ in range(16):
            score += config["lr"]
            tune.report({"score": score})

    tuner = Tuner(
        objective,
        param_space={"lr": tune.grid_search(
            [0.01 * (i + 1) for i in range(8)]
        )},
        tune_config=TuneConfig(scheduler=MedianStoppingRule(
            metric="score", mode="max", grace_period=3)),
        run_config=RunConfig(name="median8", storage_path=_exp_dir()),
    )
    grid = tuner.fit()
    assert not grid.errors
    iters = [r.metrics.get("training_iteration", 0) for r in grid]
    assert max(iters) == 16
    best = grid.get_best_result("score")
    assert best.config["lr"] == pytest.approx(0.08)


def test_with_parameters(tune_cluster):
    """Large objects bind through the object store, not per-trial configs
    (reference: tune.with_parameters)."""
    import numpy as np

    from ray_tpu.train._config import RunConfig

    big = np.arange(100_000, dtype=np.float64)

    def objective(config, data=None):
        tune.report({"score": float(data.sum()) * config["w"]})

    grid = Tuner(
        tune.with_parameters(objective, data=big),
        param_space={"w": tune.grid_search([1.0, 2.0])},
        run_config=RunConfig(name="withparams", storage_path=_exp_dir()),
    ).fit()
    assert not grid.errors
    best = grid.get_best_result("score")
    assert best.config["w"] == 2.0
    assert best.metrics["score"] == pytest.approx(big.sum() * 2.0)
