"""Shared fixtures (modeled on reference python/ray/tests/conftest.py).

JAX-related tests run on a virtual 8-device CPU mesh: the env vars must be set
before jax is first imported anywhere in the process.
"""

import os
import sys

# Force CPU: the ambient env may point JAX_PLATFORMS at real TPU hardware,
# but tests must run chip-free on the virtual 8-device mesh (SURVEY.md §4).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 " + os.environ.get("XLA_FLAGS", "")
)

# A pytest plugin may have imported jax before this file ran, baking the
# ambient JAX_PLATFORMS into its config; override it (backends are lazy, so
# this works as long as no array has touched a device yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    """Boot a real single-node cluster for the duration of one test
    (reference: conftest.py ray_start_regular :419)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    yield
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-raylet-on-one-machine cluster (reference: cluster_utils.Cluster)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=False)
    yield cluster
    cluster.shutdown()
