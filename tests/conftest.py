"""Shared fixtures (modeled on reference python/ray/tests/conftest.py).

JAX-related tests run on a virtual 8-device CPU mesh: the env vars must be set
before jax is first imported anywhere in the process.
"""

import math
import os
import sys

# Force CPU: the ambient env may point JAX_PLATFORMS at real TPU hardware,
# but tests must run chip-free on the virtual 8-device mesh (SURVEY.md §4).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 " + os.environ.get("XLA_FLAGS", "")
)
# Validate every RPC payload against the typed wire contracts
# (_private/schema.py) in all cluster tests — contract drift fails loudly.
os.environ.setdefault("RTPU_VALIDATE_RPC", "1")
# One dashboard-agent process per raylet is pure boot cost on a 1-core CI
# box; tests that exercise the agent re-enable it explicitly (test_agent.py).
os.environ.setdefault("RTPU_dashboard_agent", "0")

# A pytest plugin may have imported jax before this file ran, baking the
# ambient JAX_PLATFORMS into its config; override it (backends are lazy, so
# this works as long as no array has touched a device yet).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# ---------------------------------------------------------------- timeout
# The reference sets a 180 s default timeout in pytest.ini so one hung test
# cannot brick CI. pytest-timeout isn't available in this image, so use
# SIGALRM: it interrupts the main thread even when it is blocked in a
# syscall (socket recv, poll loop), raising in the test body.

_TEST_TIMEOUT_S = int(os.environ.get("RAY_TPU_TEST_TIMEOUT", "180"))


def _item_timeout(item):
    # @pytest.mark.timeout(N) overrides the default, mirroring pytest-timeout's
    # marker contract (which isn't installed in this image).
    mark = item.get_closest_marker("timeout")
    if mark:
        value = mark.args[0] if mark.args else mark.kwargs.get("timeout")
        if value is not None:
            # signal.alarm(0) would CANCEL the alarm; round fractions up.
            return max(1, math.ceil(value))
    return _TEST_TIMEOUT_S


def _install_alarm(phase, item):
    import faulthandler
    import signal

    deadline = _item_timeout(item)

    def _abort(signum, frame):
        faulthandler.dump_traceback()
        raise TimeoutError(
            f"{item.nodeid} {phase} exceeded {deadline}s timeout"
        )

    old = signal.signal(signal.SIGALRM, _abort)
    signal.alarm(deadline)
    return old


def _clear_alarm(old):
    import signal

    signal.alarm(0)
    signal.signal(signal.SIGALRM, old)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_setup(item):
    old = _install_alarm("setup", item)
    try:
        yield
    finally:
        _clear_alarm(old)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    old = _install_alarm("call", item)
    try:
        yield
    finally:
        _clear_alarm(old)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_teardown(item):
    old = _install_alarm("teardown", item)
    try:
        yield
    finally:
        _clear_alarm(old)


@pytest.fixture
def ray_start_regular():
    """Boot a real single-node cluster for the duration of one test
    (reference: conftest.py ray_start_regular :419)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    yield
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Multi-raylet-on-one-machine cluster (reference: cluster_utils.Cluster)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=False)
    yield cluster
    cluster.shutdown()
