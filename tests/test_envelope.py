"""Scalability-envelope regression floors (reference:
release/benchmarks/README.md). Runs envelope.py's quick mode against a real
4-raylet cluster and asserts coarse floors — the goal is catching
regressions in completion and fan-out behavior, not absolute rates (the
box's rates live in ENVELOPE.json)."""

import importlib.util
import os
import sys

import pytest


def _load_envelope():
    path = os.path.join(os.path.dirname(__file__), "..", "envelope.py")
    spec = importlib.util.spec_from_file_location("envelope", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.timeout(600)
def test_envelope_quick_floors():
    env = _load_envelope()
    r = env.run(quick=True)

    # queued-task drain completes and sustains a sane rate
    assert r["queued_tasks"]["n"] == 5_000
    assert r["queued_tasks"]["drain_per_s"] > 300

    # hundreds of actors all come up and answer
    assert r["many_actors"]["n"] == 200
    assert r["many_actors"]["create_and_ping_per_s"] > 2

    # PG churn
    assert r["many_pgs"]["create_per_s"] > 30
    assert r["many_pgs"]["remove_per_s"] > 30

    # broadcast reaches every node via tree fan-out (>=2 sources, <=N-1
    # transfers, log rounds) — the push path, not N serial pulls
    b = r["broadcast"]
    assert b["nodes"] == 4
    assert b["distinct_sources"] >= 2
    assert b["rounds"] <= 2

    # thousands of args to one task in bounded time
    assert r["many_args"]["n"] == 1_000
    assert r["many_args"]["seconds"] < 10
