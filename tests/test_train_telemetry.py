"""Step-level training telemetry (train/_telemetry.py): recorder math with
a fake clock, metric export through util.metrics, HBM absent-on-CPU,
TrainStep integration, session.report auto-attach, and SPAN events landing
in the timeline dump.

CPU-only (JAX_PLATFORMS=cpu via conftest); everything here rides the fast
marker — the cluster tests use the tiniest possible model/loops.
"""

import json
import time
import urllib.request

import pytest

from ray_tpu.train._telemetry import (
    StepRecorder,
    estimate_flops_per_token,
    peak_flops_per_device,
    set_current_recorder,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _recorder(clock, **kw):
    kw.setdefault("publish_interval_s", 0.0)
    kw.setdefault("devices", [])
    kw.setdefault("emit_spans", False)
    return StepRecorder(clock=clock, wall_clock=clock, **kw)


@pytest.mark.fast
def test_goodput_and_throughput_math():
    clk = FakeClock()
    rec = _recorder(clk)
    # compile call: 2s, booked as compile not productive
    clk.advance(2.0)
    rec.record_step(2.0, compile_step=True)
    # 8 productive steps of 0.25s, back to back
    for _ in range(8):
        clk.advance(0.25)
        rec.record_step(0.25, tokens=1024, examples=8)
    assert rec.steps == 9
    assert rec.productive_steps == 8
    assert rec.compile_s == pytest.approx(2.0)
    assert rec.productive_s == pytest.approx(2.0)
    # elapsed 4s, productive 2s
    assert rec.goodput() == pytest.approx(0.5)
    assert rec.tokens_per_second() == pytest.approx(8 * 1024 / 2.0)
    assert rec.examples_per_second() == pytest.approx(32.0)
    # a 4s stall (driver pause / restart) halves goodput again
    clk.advance(4.0)
    assert rec.goodput() == pytest.approx(0.25)
    s = rec.summary()
    assert s["steps"] == 9
    assert s["step_time_s"] == pytest.approx(0.25)
    assert s["compile_time_s"] == pytest.approx(2.0)


@pytest.mark.fast
def test_mfu_from_flops_per_step():
    clk = FakeClock()
    rec = _recorder(clk, flops_per_step=1e9, peak_flops=1e12, n_devices=2)
    clk.advance(1.0)
    rec.record_step(1.0, compile_step=True)
    for _ in range(4):
        clk.advance(0.5)
        rec.record_step(0.5)
    # 4 steps * 1e9 FLOPs over 2s on 2 chips of 1e12 peak
    assert rec.mfu() == pytest.approx(4e9 / 2.0 / 2e12)
    # multi-step scan records count as `steps` optimizer steps
    clk.advance(1.0)
    rec.record_step(1.0, steps=10)
    assert rec.productive_steps == 14
    assert rec.mfu() == pytest.approx(14e9 / 3.0 / 2e12)


@pytest.mark.fast
def test_mfu_from_flops_per_token_and_unknown_device():
    clk = FakeClock()
    rec = _recorder(clk, flops_per_token=6e6, peak_flops=1e12, n_devices=1)
    clk.advance(0.5)
    rec.record_step(0.5, tokens=2000)
    assert rec.mfu() == pytest.approx(6e6 * 2000 / 0.5 / 1e12)
    # no peak (CPU device kind) -> MFU honestly absent, not fabricated
    rec2 = _recorder(clk, flops_per_step=1e9)
    rec2.record_step(0.5)
    assert rec2.mfu() is None
    assert peak_flops_per_device("cpu") is None
    assert peak_flops_per_device("TPU v4") == pytest.approx(275e12)


@pytest.mark.fast
def test_flops_estimate_from_model_config():
    from ray_tpu.models.gpt2 import GPT2Config

    cfg = GPT2Config.tiny()
    est = estimate_flops_per_token(cfg)
    # 6 * (12 L d^2 + vocab d) for tiny: L=2, d=128, vocab=512
    assert est == pytest.approx(6 * (12 * 2 * 128 * 128 + 512 * 128))
    assert estimate_flops_per_token(object()) is None


@pytest.mark.fast
def test_hbm_gauge_absent_on_cpu():
    """device.memory_stats() returns None on CPU — the recorder must not
    crash nor emit an HBM gauge."""
    import jax

    clk = FakeClock()
    rec = StepRecorder(clock=clk, wall_clock=clk, publish_interval_s=0.0,
                       devices=jax.local_devices(), emit_spans=False)
    rec.record_step(0.1)
    assert rec.hbm_bytes_in_use() == {}
    assert "hbm_bytes_in_use" not in rec.summary()


@pytest.mark.fast
def test_hbm_gauge_present_with_stats():
    class FakeDev:
        platform = "tpu"
        id = 0
        device_kind = "TPU v5e"

        def memory_stats(self):
            return {"bytes_in_use": 123456}

    clk = FakeClock()
    rec = StepRecorder(clock=clk, wall_clock=clk, publish_interval_s=0.0,
                       devices=[FakeDev()], emit_spans=False)
    rec.record_step(0.1)
    assert rec.hbm_bytes_in_use() == {"tpu:0": 123456.0}
    assert rec.summary()["hbm_bytes_in_use"] == 123456.0


@pytest.mark.fast
def test_metrics_reach_util_metrics_records():
    from ray_tpu.util import metrics as um

    um.drain_records()  # isolate from other tests' leftovers
    clk = FakeClock()
    rec = _recorder(clk, flops_per_step=1e9, peak_flops=1e12, n_devices=1)
    clk.advance(1.0)
    rec.record_step(1.0, compile_step=True)
    for _ in range(3):
        clk.advance(0.2)
        rec.record_step(0.2, tokens=100, examples=2)
    by_name = {}
    for r in um.drain_records():
        by_name.setdefault(r["name"], r)
    assert by_name["ray_tpu_train_steps_total"]["value"] == 4
    assert by_name["ray_tpu_train_step_seconds"]["count"] == 3
    assert by_name["ray_tpu_train_step_seconds"]["sum"] == pytest.approx(0.6)
    assert by_name["ray_tpu_train_goodput_ratio"]["value"] == pytest.approx(
        0.6 / 1.6)
    assert by_name["ray_tpu_train_tokens_per_second"]["value"] == pytest.approx(
        300 / 0.6)
    assert by_name["ray_tpu_train_mfu_ratio"]["value"] == pytest.approx(
        3e9 / 0.6 / 1e12)
    assert by_name["ray_tpu_train_compile_seconds"]["value"] == pytest.approx(
        1.0)


@pytest.mark.fast
def test_session_report_auto_attaches_telemetry():
    from ray_tpu.train._session import (
        TrainContext, init_session, report, shutdown_session,
    )

    clk = FakeClock()
    s = init_session(TrainContext(0, 1, 0, 1, "127.0.0.1"), None,
                     pipeline_depth=4)
    try:
        rec = _recorder(clk)
        set_current_recorder(rec)
        clk.advance(0.5)
        rec.record_step(0.5, tokens=64)
        report({"loss": 1.5, "telemetry/goodput": "user-wins"})
        item = s.reports.get_nowait()
        m = item["metrics"]
        assert m["loss"] == 1.5
        assert m["telemetry/steps"] == 1
        assert m["telemetry/tokens_per_s"] == pytest.approx(128.0)
        # user-provided keys always win over auto-attached ones
        assert m["telemetry/goodput"] == "user-wins"
    finally:
        set_current_recorder(None)
        shutdown_session()


@pytest.mark.fast
def test_train_step_records_compile_and_steps():
    """TrainStep books jit cache misses as compile time (both the first
    trace AND the ambient-mesh-context recompile), and productive steps
    carry token counts."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.gpt2 import GPT2Config
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    cfg = GPT2Config.tiny(use_flash_attention=False, dtype=jnp.float32)
    ts = TrainStep(cfg, make_mesh({"dp": 8}), learning_rate=1e-3)
    assert ts.telemetry is not None
    from ray_tpu.train._telemetry import current_recorder

    assert current_recorder() is ts.telemetry
    state = ts.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    idx = rng.integers(0, cfg.vocab_size, size=(8, 32)).astype(np.int32)
    batch = {"idx": jnp.asarray(idx),
             "targets": jnp.asarray(np.roll(idx, -1, 1))}
    for _ in range(4):
        state, _ = ts.step(state, ts.shard_batch(batch))
    rec = ts.telemetry
    assert rec.steps == 4
    assert rec.compile_s > 0
    assert rec.productive_steps >= 2  # at most 2 calls were cache misses
    assert rec.productive_s > 0
    assert rec.tokens == 8 * 32 * rec.productive_steps
    # CPU: no HBM stats, no MFU (unknown peak) — absent, not wrong
    assert rec.hbm_bytes_in_use() == {}
    s = rec.summary()
    assert s["goodput"] <= 1.0


@pytest.mark.fast
def test_telemetry_opt_out():
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    cfg = GPT2Config.tiny(use_flash_attention=False, dtype=jnp.float32)
    ts = TrainStep(cfg, make_mesh({"dp": 8}), telemetry=False)
    assert ts.telemetry is None


def test_step_spans_reach_timeline_dump(ray_start_regular, tmp_path):
    """Per-step SPAN events flow task-events -> GCS -> timeline(): the
    Chrome trace must contain train_step spans with durations."""
    import ray_tpu

    rec = StepRecorder(publish_interval_s=0.0, devices=[])
    rec.record_step(0.5, compile_step=True)
    for _ in range(3):
        rec.record_step(0.02, tokens=256)
    out = tmp_path / "trace.json"
    deadline = time.time() + 20
    spans = []
    while time.time() < deadline:
        ray_tpu.timeline(str(out))
        events = json.loads(out.read_text())
        spans = [e for e in events
                 if e.get("cat") == "span"
                 and str(e.get("name", "")).startswith("train_step")]
        if len(spans) >= 4:
            break
        time.sleep(0.3)
    assert len(spans) >= 4
    compile_spans = [e for e in spans if e["name"] == "train_step.compile"]
    assert compile_spans and compile_spans[0]["dur"] == pytest.approx(
        0.5e6, rel=0.01)
    step_spans = [e for e in spans if e["name"] == "train_step"]
    assert step_spans[0]["args"]["tokens"] == "256"


def test_trainer_run_exports_prometheus_metrics(ray_start_regular, tmp_path):
    """Acceptance: a CPU-only JaxTrainer run followed by a GCS /metrics
    scrape shows the ray_tpu_train_* series, and the dashboard /api/train
    summarizes them per job."""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.train import JaxConfig, JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.gpt2 import GPT2Config
        from ray_tpu.parallel.mesh import make_mesh
        from ray_tpu.parallel.train_step import TrainStep

        cfg = GPT2Config.tiny(use_flash_attention=False, dtype=jnp.float32)
        ts = TrainStep(cfg, make_mesh({"dp": 1}, devices=jax.devices()[:1]),
                       learning_rate=1e-3)
        state = ts.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        idx = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        batch = {"idx": jnp.asarray(idx),
                 "targets": jnp.asarray(np.roll(idx, -1, 1))}
        for _ in range(3):
            state, m = ts.step(state, ts.shard_batch(batch))
        train.report({"loss": float(m["loss"])})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="telem"),
        jax_config=JaxConfig(distributed=False),
    )
    result = trainer.fit()
    # report() auto-attached the telemetry summary
    assert result.metrics["telemetry/steps"] == 3
    assert 0 < result.metrics["telemetry/goodput"] <= 1.0
    assert result.metrics["telemetry/tokens_per_s"] > 0

    from ray_tpu._private import worker as worker_mod

    port = worker_mod.global_worker.gcs.ping()["metrics_port"]
    deadline = time.time() + 25
    text = ""
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            text = r.read().decode()
        if "ray_tpu_train_step_seconds" in text:
            break
        time.sleep(0.5)
    assert "ray_tpu_train_step_seconds_bucket" in text
    assert "ray_tpu_train_steps_total" in text
    assert "ray_tpu_train_tokens_per_second" in text
    assert "ray_tpu_train_goodput_ratio" in text

    # dashboard /api/train aggregates the same series per job
    from ray_tpu.dashboard.head import DashboardHead

    head = DashboardHead(worker_mod.global_worker.gcs.address)
    status, payload = head._collect("/api/train", "GET", None, {})
    assert status == 200
    jobs = payload["jobs"]
    assert jobs, "no jobs in /api/train"
    job = next(iter(jobs.values()))
    assert job["steps"] >= 3
    assert job["tokens_per_second"] > 0
    assert job["step_seconds"]["count"] >= 1
    assert job["step_seconds"]["p50"] is not None
