"""Dataset.split ref-level semantics (reference: python/ray/data/dataset.py
split — planned over block metadata, never materialized on the driver) and
the read_binary_files / read_images datasources."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture
def data_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def _rows(block):
    from ray_tpu.data.block import block_num_rows

    return block_num_rows(block)


def test_split_row_exact_and_block_aligned(data_cluster):
    ds = rdata.range(1000, override_num_blocks=7)
    shards = ds.split(4)  # equal: 250 each, block boundaries straddled
    sizes = [sum(ray_tpu.get([_rows.remote(r) for r in s._iter_block_refs()]))
             for s in shards]
    assert sizes == [250, 250, 250, 250]
    # every row exactly once (ignoring the dropped remainder of 0 here)
    seen = sorted(
        int(v) for s in shards for b in s.iter_batches(batch_size=None)
        for v in np.asarray(b["id"]))
    assert seen == list(range(1000))

    # unequal: no rows dropped
    shards = rdata.range(10, override_num_blocks=3).split(3, equal=False)
    sizes = [sum(ray_tpu.get([_rows.remote(r) for r in s._iter_block_refs()]))
             for s in shards]
    assert sorted(sizes) == [3, 3, 4]

    with pytest.raises(ValueError):
        rdata.range(2).split(3)


def test_split_driver_memory_ceiling(data_cluster):
    """split must move whole blocks by reference and slice stragglers in
    tasks — the driver sees counts, not data (the round-4 verdict's
    driver-OOM trap)."""
    import os

    import psutil

    row_bytes = 40_000
    n_rows = 2_000  # ~80 MB total, built worker-side

    def expand(batch):
        n = len(batch["id"])
        return {
            "id": batch["id"],
            "payload": np.ones((n, row_bytes // 8), np.float64),
        }

    ds = rdata.range(n_rows, override_num_blocks=8).map_batches(expand)
    refs = list(ds._iter_block_refs())

    proc = psutil.Process(os.getpid())
    rss_before = proc.memory_info().rss
    shards = rdata.Dataset(refs).split(3, equal=False)
    shard_refs = [list(s._iter_block_refs()) for s in shards]
    rss_after = proc.memory_info().rss
    grew = rss_after - rss_before
    total = n_rows * row_bytes
    assert grew < total // 2, (
        f"driver RSS grew {grew / 1e6:.0f} MB splitting a "
        f"{total / 1e6:.0f} MB dataset — looks driver-materializing"
    )
    counts = [sum(ray_tpu.get([_rows.remote(r) for r in refs_]))
              for refs_ in shard_refs]
    assert sum(counts) == n_rows and max(counts) - min(counts) <= 1


def test_read_binary_files(tmp_path, data_cluster):
    (tmp_path / "a.bin").write_bytes(b"alpha")
    (tmp_path / "b.bin").write_bytes(b"bravo" * 100)
    (tmp_path / "skip.txt").write_text("nope")
    ds = rdata.read_binary_files(
        str(tmp_path), include_paths=True, file_extensions=["bin"])
    rows = {}
    for batch in ds.iter_batches(batch_size=None):
        for path, payload in zip(batch["path"], batch["bytes"]):
            rows[str(path).rsplit("/", 1)[-1]] = bytes(payload)
    assert rows == {"a.bin": b"alpha", "b.bin": b"bravo" * 100}


def test_read_images(tmp_path, data_cluster):
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(6):
        arr = rng.integers(0, 255, (8 + i, 10, 3), np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")
    # resized decode stacks dense
    ds = rdata.read_images(str(tmp_path), size=(16, 12), mode="RGB")
    total = 0
    for batch in ds.iter_batches(batch_size=None):
        imgs = np.asarray(batch["image"])
        assert imgs.shape[1:] == (16, 12, 3)
        total += imgs.shape[0]
    assert total == 6
    # native-size decode keeps per-image arrays
    ds2 = rdata.read_images(str(tmp_path))
    shapes = set()
    for batch in ds2.iter_batches(batch_size=None):
        for img in batch["image"]:
            shapes.add(np.asarray(img).shape)
    assert (8, 10, 3) in shapes and (13, 10, 3) in shapes
