"""Checkpoint-storage tests (reference: train/_internal/storage.py:352 —
URI-addressed persistence; the mock:// scheme simulates S3/GCS with a
detached actor so the no-shared-FS path is proven without a cloud)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._storage import get_storage, is_remote_uri


@pytest.fixture(scope="module")
def storage_cluster():
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_is_remote_uri():
    assert not is_remote_uri("/tmp/x")
    assert not is_remote_uri("file:///tmp/x")
    assert not is_remote_uri(None)
    assert is_remote_uri("mock://bucket/pre")
    assert is_remote_uri("s3://bucket/pre")


def test_mock_storage_roundtrip(storage_cluster, tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.txt").write_text("alpha")
    (src / "sub" / "b.bin").write_bytes(b"\x00\x01")

    st = get_storage("mock://bucket/exp1")
    uri = st.upload_dir(str(src), "checkpoint_000000")
    assert uri == "mock://bucket/exp1/checkpoint_000000"
    assert st.list_dirs() == ["checkpoint_000000"]

    dest = tmp_path / "dest"
    st.download_dir("checkpoint_000000", str(dest))
    assert (dest / "a.txt").read_text() == "alpha"
    assert (dest / "sub" / "b.bin").read_bytes() == b"\x00\x01"

    st.delete_dir("checkpoint_000000")
    assert st.list_dirs() == []


def test_checkpoint_from_uri(storage_cluster, tmp_path):
    src = tmp_path / "ck"
    src.mkdir()
    (src / "w.npy").write_bytes(b"npy!")
    st = get_storage("mock://bucket/exp2")
    uri = st.upload_dir(str(src), "checkpoint_000001")

    ckpt = Checkpoint.from_uri(uri)
    assert ckpt.uri == uri
    with ckpt.as_directory() as d:
        assert open(os.path.join(d, "w.npy"), "rb").read() == b"npy!"


def test_trainer_with_remote_storage(storage_cluster, tmp_path):
    """End-to-end: JaxTrainer persists checkpoints to mock:// storage via
    worker-side uploads; result checkpoint is a URI; resume works."""

    def loop(config):
        import os as _os
        import tempfile

        from ray_tpu import train

        start = 0
        ck = train.get_checkpoint()
        if ck is not None:
            with ck.as_directory() as d:
                start = int(open(_os.path.join(d, "step.txt")).read())
        for step in range(start, start + 3):
            d = tempfile.mkdtemp()
            with open(_os.path.join(d, "step.txt"), "w") as f:
                f.write(str(step + 1))
            train.report({"step": step + 1},
                         checkpoint=Checkpoint.from_directory(d))

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="remote_exp",
            storage_path="mock://bucket/results",
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3
    assert result.checkpoint is not None
    assert result.checkpoint.uri.startswith("mock://bucket/results/remote_exp")
    with result.checkpoint.as_directory() as d:
        assert open(os.path.join(d, "step.txt")).read() == "3"
    # retention: only 2 checkpoints remain in the bucket (fit() runs as a
    # 1-trial Tune run, which roots the trainer under worker_of_<trial>)
    st = get_storage("mock://bucket/results/remote_exp")
    subdirs = st.list_dirs()
    ckpt_dirs = [d for d in subdirs if d.startswith("checkpoint_")]
    if not ckpt_dirs:
        inner = next(d for d in subdirs if d.startswith("worker_of"))
        ckpt_dirs = [
            d for d in get_storage(st.uri_of(inner)).list_dirs()
            if d.startswith("checkpoint_")
        ]
    assert len(ckpt_dirs) == 2

    # resume from the persisted URI checkpoint
    trainer2 = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="remote_exp2",
                             storage_path="mock://bucket/results"),
        resume_from_checkpoint=result.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.metrics["step"] == 6
