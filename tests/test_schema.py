"""Typed wire-contract tests (reference: the protobuf surface —
src/ray/protobuf/*.proto; here _private/schema.py validated at the RPC
boundary). The whole test suite also runs with RTPU_VALIDATE_RPC=1 (see
conftest.py), so every cluster test doubles as a contract check."""

import pytest

from ray_tpu._private import schema

pytestmark = pytest.mark.fast  # pure-unit: no cluster boot


def test_valid_payload_passes():
    schema.validate(schema.GCS_SCHEMAS, "KVPut",
                    {"ns": b"n", "key": b"k", "value": b"v"})
    schema.validate(schema.GCS_SCHEMAS, "KVPut",
                    {"ns": "n", "key": "k", "value": "v", "overwrite": False})


def test_missing_required_field():
    with pytest.raises(schema.SchemaError, match="missing required"):
        schema.validate(schema.GCS_SCHEMAS, "KVPut", {"ns": b"n", "key": b"k"})


def test_wrong_type():
    with pytest.raises(schema.SchemaError, match="expected"):
        schema.validate(schema.GCS_SCHEMAS, "Heartbeat", {"node_id": "hex"})


def test_optional_field_none_ok():
    schema.validate(
        schema.RAYLET_SCHEMAS, "RequestWorkerLease",
        {"job_id": b"j", "resources": {"CPU": 1}, "runtime_env": None},
    )


def test_unknown_method_passes():
    schema.validate(schema.GCS_SCHEMAS, "SomeFutureMethod", {"x": 1})


def test_unknown_fields_allowed():
    # forward compatibility, like proto3 unknown fields
    schema.validate(schema.GCS_SCHEMAS, "Heartbeat",
                    {"node_id": b"n", "new_field": 42})


def test_non_map_payload_rejected():
    with pytest.raises(schema.SchemaError, match="must be a map"):
        schema.validate(schema.GCS_SCHEMAS, "Heartbeat", [1, 2])


def test_validator_disabled_without_env(monkeypatch):
    monkeypatch.delenv("RTPU_VALIDATE_RPC", raising=False)
    assert schema.make_validator(schema.GCS_SCHEMAS) is None
    monkeypatch.setenv("RTPU_VALIDATE_RPC", "1")
    v = schema.make_validator(schema.GCS_SCHEMAS)
    assert v is not None
    with pytest.raises(schema.SchemaError):
        v("Heartbeat", {})
