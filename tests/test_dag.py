"""Compiled actor DAG tests (reference: python/ray/dag/tests/)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag.node import InputNode, MultiOutputNode


@ray_tpu.remote
class Doubler:
    def apply(self, x):
        return x * 2


@ray_tpu.remote
class Adder:
    def __init__(self, k=1):
        self.k = k

    def apply(self, x):
        return x + self.k

    def add_pair(self, a, b):
        return a + b

    def boom(self, x):
        raise ValueError("boom")


@pytest.fixture
def dag_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    ray_tpu.shutdown()


def test_eager_dag_execute(dag_cluster):
    a = Adder.remote(10)
    with InputNode() as inp:
        out = a.apply.bind(inp)
    ref = out.execute(5)
    assert ray_tpu.get(ref) == 15


def test_compiled_three_stage_pipeline(dag_cluster):
    a = Adder.remote(1)
    b = Doubler.remote()
    c = Adder.remote(100)
    with InputNode() as inp:
        x = a.apply.bind(inp)
        y = b.apply.bind(x)
        z = c.apply.bind(y)
    dag = z.experimental_compile()
    try:
        for i in range(50):
            assert dag.execute(i).get() == (i + 1) * 2 + 100
    finally:
        dag.teardown()


def test_compiled_dag_pipelining_overlap(dag_cluster):
    """Stages overlap: 3 stages x 50ms, 6 items. Serial would be 900ms;
    pipelined is ~(3 + 5) * 50ms = 400ms. Assert well under serial."""

    @ray_tpu.remote
    class Slow:
        def apply(self, x):
            time.sleep(0.05)
            return x + 1

    s1, s2, s3 = Slow.remote(), Slow.remote(), Slow.remote()
    with InputNode() as inp:
        out = s3.apply.bind(s2.apply.bind(s1.apply.bind(inp)))
    dag = out.experimental_compile()
    try:
        dag.execute(0).get()  # warm
        t0 = time.perf_counter()
        refs = [dag.execute(i) for i in range(6)]
        outs = [r.get() for r in refs]
        dt = time.perf_counter() - t0
        assert outs == [i + 3 for i in range(6)]
        assert dt < 0.75, f"no pipelining: {dt:.2f}s"
    finally:
        dag.teardown()


def test_compiled_dag_fan_out_fan_in(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(0)
    with InputNode() as inp:
        x = a.apply.bind(inp)
        y = b.apply.bind(inp)
        z = c.add_pair.bind(x, y)
    dag = z.experimental_compile()
    try:
        for i in range(10):
            assert dag.execute(i).get() == (i + 1) + (i + 2)
    finally:
        dag.teardown()


def test_compiled_dag_multi_output(dag_cluster):
    a = Adder.remote(1)
    b = Doubler.remote()
    with InputNode() as inp:
        x = a.apply.bind(inp)
        y = b.apply.bind(inp)
    dag = MultiOutputNode([x, y]).experimental_compile()
    try:
        assert dag.execute(5).get() == [6, 10]
    finally:
        dag.teardown()


def test_compiled_dag_numpy_payloads(dag_cluster):
    b = Doubler.remote()
    with InputNode() as inp:
        out = b.apply.bind(inp)
    dag = b and out.experimental_compile()
    try:
        arr = np.arange(100_000, dtype=np.float32)
        got = dag.execute(arr).get()
        np.testing.assert_array_equal(got, arr * 2)
    finally:
        dag.teardown()


def test_compiled_dag_error_propagation(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(1)
    with InputNode() as inp:
        out = b.apply.bind(a.boom.bind(inp))
    dag = out.experimental_compile()
    try:
        with pytest.raises(ValueError, match="boom"):
            dag.execute(1).get()
        # the DAG stays usable after an application error
        with pytest.raises(ValueError, match="boom"):
            dag.execute(2).get()
    finally:
        dag.teardown()


def test_compiled_dag_faster_than_uncompiled(dag_cluster):
    """The headline property: per-step overhead beats .remote() chains."""
    a = Adder.remote(1)
    b = Doubler.remote()
    with InputNode() as inp:
        out = b.apply.bind(a.apply.bind(inp))

    # uncompiled: 2 actor submissions + gets per step
    n = 200
    ray_tpu.get(b.apply.remote(ray_tpu.get(a.apply.remote(0))))
    t0 = time.perf_counter()
    for i in range(n):
        ray_tpu.get(b.apply.remote(ray_tpu.get(a.apply.remote(i))))
    t_uncompiled = time.perf_counter() - t0

    dag = out.experimental_compile()
    try:
        dag.execute(0).get()
        t0 = time.perf_counter()
        for i in range(n):
            assert dag.execute(i).get() == (i + 1) * 2
        t_compiled = time.perf_counter() - t0
    finally:
        dag.teardown()
    speedup = t_uncompiled / t_compiled
    print(f"\ncompiled {n / t_compiled:,.0f} steps/s vs "
          f"uncompiled {n / t_uncompiled:,.0f} steps/s ({speedup:.1f}x)")
    assert speedup > 2.0, f"compiled DAG only {speedup:.2f}x faster"


def test_compiled_dag_cross_node_pipeline():
    """Multi-host pipeline parallelism: stages on different nodes connected
    by socket channels (the DCN hop), same-node edges on shared memory
    (reference: compiled_dag_node.py:391 + the NCCL channel's role,
    torch_tensor_nccl_channel.py:191)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"resources": {"CPU": 3, "stage1": 1}},
    )
    cluster.add_node(resources={"CPU": 2, "stage2": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        a = Adder.options(resources={"stage1": 1}).remote(1)  # head node
        b = Doubler.options(resources={"stage2": 1}).remote()  # second node
        c = Adder.options(resources={"stage2": 1}).remote(100)  # second node

        # confirm the placement is actually cross-node
        def node_of(h):
            return ray_tpu.get(h.__ray_call__.remote(
                lambda self: __import__("ray_tpu")
                .get_runtime_context().get_node_id()
            ))

        assert node_of(a) != node_of(b)
        assert node_of(b) == node_of(c)

        with InputNode() as inp:
            x = a.apply.bind(inp)     # driver -> head actor (shm)
            y = b.apply.bind(x)       # head -> node2 (socket)
            z = c.apply.bind(y)       # node2 -> node2 (shm on node2)
        dag = z.experimental_compile()
        try:
            for i in range(30):
                assert dag.execute(i).get() == (i + 1) * 2 + 100
            # numpy payload across the socket edge
            with InputNode() as inp2:
                w = b.apply.bind(inp2)
            dag2 = w.experimental_compile()
            try:
                arr = np.arange(1000, dtype=np.float32)
                out = dag2.execute(arr).get()
                assert np.allclose(out, arr * 2)
            finally:
                dag2.teardown()
        finally:
            dag.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_compiled_dag_cross_node_error_propagation():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 2}}
    )
    cluster.add_node(resources={"CPU": 2, "far": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        a = Adder.options(resources={"far": 1}).remote(1)
        with InputNode() as inp:
            out = a.boom.bind(inp)
        dag = out.experimental_compile()
        try:
            with pytest.raises(ValueError, match="boom"):
                dag.execute(1).get()
            # channel stays usable for the next tick after an error
            with pytest.raises(ValueError, match="boom"):
                dag.execute(2).get()
        finally:
            dag.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
